"""Trainer step builders: sharding stability and opt-state spec derivation.

The reference relies on its response cache to make repeat iterations cheap
(response_cache.h:43-92); the jit analogue is *compiling exactly once*. These
tests pin the subtle failure mode where a host-created optimizer state (its
scalar avals carry no mesh context) silently recompiles the whole train step
on the second call.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu import trainer
from horovod_tpu.models import transformer as tr
from horovod_tpu.parallel import mesh as mesh_mod


def _tiny_setup(mesh):
    cfg = tr.TransformerConfig.tiny()
    model = tr.TransformerLM(cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 64)),
        jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
    return model, params, tokens


class TestOptStateSpecs:
    def test_mirrors_param_specs_and_replicates_scalars(self):
        params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
        specs = {"w": P("tp", None), "b": P()}
        tx = optax.adamw(1e-3)
        out = trainer.opt_state_specs(tx, params, specs)
        adam = out[0]
        assert adam.count == P()
        assert adam.mu["w"] == P("tp", None)
        assert adam.mu["b"] == P()
        assert adam.nu["w"] == P("tp", None)

    def test_works_with_distributed_optimizer(self):
        import horovod_tpu as hvd
        params = {"w": jnp.ones((4, 4))}
        tx = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
        out = trainer.opt_state_specs(
            tx, params, {"w": P()})
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda s: isinstance(s, P))
        assert all(isinstance(s, P) for s in leaves)


class TestGradientScaling:
    def test_data_parallel_update_matches_analytic_gd(self, hvd):
        """The distributed step must equal full-batch GD exactly — guards
        against shard_map autodiff pre-summing grads of replicated params
        (which silently applies size()× gradients)."""
        import horovod_tpu as hvd_mod
        mesh = hvd.mesh()
        axis = mesh.axis_names[0]
        X = np.random.RandomState(0).randn(64, 4).astype(np.float32)
        true_w = np.array([[2.0], [-3.0], [0.5], [1.0]], np.float32)
        Y = X @ true_w

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((x @ p["w"] - y) ** 2)

        tx = hvd_mod.DistributedOptimizer(optax.sgd(0.1))
        params = {"w": jnp.zeros((4, 1))}
        step = trainer.make_data_parallel_step(loss_fn, tx, mesh,
                                               donate=False)
        opt_state = trainer.init_opt_state(tx, params, mesh)
        batch = trainer.place((jnp.asarray(X), jnp.asarray(Y)), mesh,
                              (P(axis), P(axis)))
        p1, _, _ = step(params, opt_state, batch)
        w0 = np.zeros((4, 1), np.float32)
        w1 = w0 - 0.1 * (2.0 / 64.0 * X.T @ (X @ w0 - Y))
        np.testing.assert_allclose(np.asarray(p1["w"]), w1, rtol=1e-5)

    def test_data_parallel_training_converges(self, hvd):
        mesh = hvd.mesh()
        axis = mesh.axis_names[0]
        X = np.random.RandomState(1).randn(64, 4).astype(np.float32)
        true_w = np.array([[2.0], [-3.0], [0.5], [1.0]], np.float32)
        Y = X @ true_w

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((x @ p["w"] - y) ** 2)

        tx = optax.sgd(0.1)
        params = {"w": jnp.zeros((4, 1))}
        step = trainer.make_data_parallel_step(loss_fn, tx, mesh,
                                               donate=False)
        opt_state = trainer.init_opt_state(tx, params, mesh)
        batch = trainer.place((jnp.asarray(X), jnp.asarray(Y)), mesh,
                              (P(axis), P(axis)))
        for _ in range(200):
            params, opt_state, loss = step(params, opt_state, batch)
            # block each step: hundreds of in-flight 8-device collective
            # programs can starve the CPU backend's rendezvous (the real
            # TPU path has hardware queues and doesn't need this)
            loss.block_until_ready()
        assert float(loss) < 1e-3
        np.testing.assert_allclose(np.asarray(params["w"]), true_w,
                                   atol=1e-2)


class TestSingleCompile:
    def test_gspmd_step_compiles_once(self, hvd):
        mesh = mesh_mod.build_mesh(dp=2, tp=2, sp=2)
        model, params, tokens = _tiny_setup(mesh)
        loss_fn = tr.lm_loss_fn(model)
        tx = optax.adamw(1e-3)
        specs = tr.param_specs(params)
        step, pshard, bshard = trainer.make_gspmd_step(
            loss_fn, tx, mesh, specs, tr.batch_spec(sp=True), params=params)
        params = jax.tree_util.tree_map(jax.device_put, params, pshard)
        opt_state = trainer.init_opt_state(tx, params, mesh, specs)
        tokens = jax.device_put(tokens, bshard)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tokens)
        assert jnp.isfinite(loss)
        assert step._cache_size() == 1, (
            "train step recompiled: opt_state shardings are not stable "
            "across calls")

    def test_bare_tx_init_would_recompile(self, hvd):
        # documents WHY init_opt_state exists: the naive host-side tx.init
        # costs a second compilation.
        mesh = mesh_mod.build_mesh(dp=2, tp=2, sp=2)
        model, params, tokens = _tiny_setup(mesh)
        loss_fn = tr.lm_loss_fn(model)
        tx = optax.adamw(1e-3)
        specs = tr.param_specs(params)
        step, pshard, bshard = trainer.make_gspmd_step(
            loss_fn, tx, mesh, specs, tr.batch_spec(sp=True), params=params)
        params = jax.tree_util.tree_map(jax.device_put, params, pshard)
        opt_state = tx.init(params)  # deliberately NOT init_opt_state
        tokens = jax.device_put(tokens, bshard)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tokens)
        assert step._cache_size() >= 1  # smoke: still correct, just slower

    def test_data_parallel_step_compiles_once(self, hvd):
        mesh = hvd.mesh()

        def loss_fn(p, batch):
            x, y = batch
            pred = x @ p["w"]
            return jnp.mean((pred - y) ** 2)

        tx = optax.sgd(0.1, momentum=0.9)
        params = trainer.replicate({"w": jnp.ones((4, 2))}, mesh)
        step = trainer.make_data_parallel_step(loss_fn, tx, mesh,
                                               donate=False)
        opt_state = trainer.init_opt_state(tx, params, mesh)
        axis = mesh.axis_names[0]
        batch = trainer.place((jnp.ones((8, 4)), jnp.zeros((8, 2))), mesh,
                              (P(axis), P(axis)))
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, batch)
        assert step._cache_size() == 1


class TestMultiStep:
    def test_multi_step_matches_sequential_steps(self, hvd):
        """make_gspmd_multi_step (device-side lax.scan training loop,
        the bench's dispatch-free timing path) must produce the SAME
        params/opt_state/loss as n sequential make_gspmd_step calls."""
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)
        mesh = mesh_mod.build_mesh(dp=4, tp=2)
        model = tr.TransformerLM(cfg)
        n_steps, batch, seq = 3, 8, 32
        rng = np.random.RandomState(0)
        all_toks = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (n_steps, batch, seq)),
            jnp.int32)
        params0 = model.init(jax.random.PRNGKey(0),
                             all_toks[0])["params"]
        tx = optax.adamw(1e-2)
        loss_fn = tr.lm_loss_fn(model)
        specs = tr.param_specs(params0)

        # sequential reference
        step, pshard, bshard = trainer.make_gspmd_step(
            loss_fn, tx, mesh, specs, tr.batch_spec(), params=params0,
            donate=False)
        params = jax.tree_util.tree_map(jax.device_put, params0, pshard)
        opt_state = trainer.init_opt_state(tx, params, mesh, specs)
        for i in range(n_steps):
            params, opt_state, loss = step(
                params, opt_state, jax.device_put(all_toks[i], bshard))

        # device-side scan
        mstep, mpshard, mbshard = trainer.make_gspmd_multi_step(
            loss_fn, tx, mesh, specs, tr.batch_spec(), params=params0,
            donate=False)
        mparams = jax.tree_util.tree_map(jax.device_put, params0, mpshard)
        mopt = trainer.init_opt_state(tx, mparams, mesh, specs)
        mparams, mopt, mloss = mstep(
            mparams, mopt, jax.device_put(all_toks, mbshard))

        np.testing.assert_allclose(float(mloss), float(loss), rtol=1e-5)
        for (ka, a), (kb, b) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(mparams),
                       key=lambda kv: str(kv[0])),
                sorted(jax.tree_util.tree_leaves_with_path(params),
                       key=lambda kv: str(kv[0])),
                strict=True):
            assert str(ka) == str(kb)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-6,
                                       err_msg=str(ka))
