"""Launch-layer tests (reference test strategy: run/ services are exercised
end-to-end in test_spark.py:51-110; here we unit-test the pieces plus a real
local hvdrun launch)."""

import base64
import io
import os
import subprocess
import sys
import time

import pytest

from horovod_tpu.run import cache as cache_mod
from horovod_tpu.run import exec_util, hosts, network, secret, services
from horovod_tpu.run.cli import run_command_on_hosts
from horovod_tpu.run.settings import Settings, Timeout, TimeoutException


class TestWire:
    def test_roundtrip(self):
        key = secret.make_secret_key()
        wire = network.Wire(key)
        buf = io.BytesIO()
        wire.write({"hello": [1, 2, 3]}, buf)
        buf.seek(0)
        assert wire.read(buf) == {"hello": [1, 2, 3]}

    def test_tampered_payload_rejected(self):
        key = secret.make_secret_key()
        wire = network.Wire(key)
        buf = io.BytesIO()
        wire.write("payload", buf)
        raw = bytearray(buf.getvalue())
        raw[-1] ^= 0xFF
        with pytest.raises(RuntimeError, match="Security error"):
            wire.read(io.BytesIO(bytes(raw)))

    def test_wrong_key_rejected(self):
        w1 = network.Wire(secret.make_secret_key())
        w2 = network.Wire(secret.make_secret_key())
        buf = io.BytesIO()
        w1.write("x", buf)
        buf.seek(0)
        with pytest.raises(RuntimeError, match="Security error"):
            w2.read(buf)


class TestServices:
    def test_ping_and_register(self):
        key = secret.make_secret_key()
        driver = services.LaunchDriverService(num_tasks=2, key=key)
        try:
            addrs = {"lo": [("127.0.0.1", driver.port)]}
            client = services.LaunchDriverClient(addrs, key)
            client.register_task(0, {"lo": [("127.0.0.1", 1)]}, "h0")
            client.register_task(1, {"lo": [("127.0.0.1", 2)]}, "h1")
            driver.wait_for_initial_registration(
                Timeout(5, "registration timed out"))
            assert client.all_task_addresses(1) == {"lo": [("127.0.0.1", 2)]}
            assert driver.task_host_hashes() == {0: "h0", 1: "h1"}
        finally:
            driver.shutdown()

    def test_wrong_key_cannot_connect(self):
        key = secret.make_secret_key()
        driver = services.LaunchDriverService(num_tasks=1, key=key)
        try:
            addrs = {"lo": [("127.0.0.1", driver.port)]}
            with pytest.raises(network.NoValidAddressesFound):
                services.LaunchDriverClient(addrs, secret.make_secret_key(),
                                            probe_timeout=0.5)
        finally:
            driver.shutdown()

    def test_common_interfaces_intersection(self):
        key = secret.make_secret_key()
        driver = services.LaunchDriverService(num_tasks=2, key=key)
        try:
            client = services.LaunchDriverClient(
                {"lo": [("127.0.0.1", driver.port)]}, key)
            client.register_task_to_task_addresses(
                0, {"eth0": [("10.0.0.1", 1)], "ib0": [("10.1.0.1", 1)]})
            client.register_task_to_task_addresses(
                1, {"eth0": [("10.0.0.2", 1)]})
            driver.wait_for_task_to_task_addresses(Timeout(5, "t"))
            assert driver.common_interfaces() == {"eth0"}
        finally:
            driver.shutdown()

    def test_task_service_runs_command(self, tmp_path):
        key = secret.make_secret_key()
        task = services.LaunchTaskService(0, key)
        try:
            client = services.LaunchTaskClient(
                0, {"lo": [("127.0.0.1", task.port)]}, key)
            marker = tmp_path / "ran"
            client.run_command(
                [sys.executable, "-c",
                 f"open({str(marker)!r}, 'w').write('ok')"])
            deadline = time.time() + 10
            while time.time() < deadline:
                terminated, code = client.command_exit_code()
                if terminated:
                    break
                time.sleep(0.1)
            assert terminated and code == 0
            assert marker.read_text() == "ok"
        finally:
            task.shutdown()


class TestHosts:
    def test_parse(self):
        hs = hosts.parse_hosts("a:2,b:4,c")
        assert [(h.hostname, h.slots) for h in hs] == \
            [("a", 2), ("b", 4), ("c", 1)]

    def test_parse_empty_raises(self):
        with pytest.raises(ValueError):
            hosts.parse_hosts(" , ")

    def test_expand_slots(self):
        hs = hosts.parse_hosts("a:2,b:1")
        expanded = hosts.expand_slots(hs)
        assert [(r, h.hostname, lr) for r, h, lr in expanded] == \
            [(0, "a", 0), (1, "a", 1), (2, "b", 0)]

    def test_localhost_is_local(self):
        assert hosts.is_local("localhost")
        assert hosts.is_local("127.0.0.1")
        assert not hosts.is_local("definitely-not-this-host.example")

    def test_host_hash_stable(self):
        assert hosts.host_hash() == hosts.host_hash()


class TestExecUtil:
    def test_env_filter(self):
        env = exec_util.filtered_env({"HVD_PROCESS_ID": 3})
        assert env["HVD_PROCESS_ID"] == "3"
        assert "OLDPWD" not in env

    def test_forwarded_flags(self):
        flags = exec_util.forwarded_env_flags(
            {"HOROVOD_FUSION_THRESHOLD": "1", "HOME": "/x", "OLDPWD": "/y"})
        assert flags == ["HOROVOD_FUSION_THRESHOLD=1"]

    def test_safe_execute_and_terminate(self):
        proc = exec_util.safe_execute([sys.executable, "-c",
                                       "import time; time.sleep(60)"])
        assert proc.poll() is None
        exec_util.terminate_tree(proc, grace_s=2.0)
        assert proc.wait(timeout=5) != 0


class TestCacheAndTimeout:
    def test_cache_roundtrip_and_ttl(self, tmp_path):
        c = cache_mod.Cache(cache_dir=str(tmp_path), ttl_s=1000)
        assert c.get(("ssh", "h")) is None
        c.put(("ssh", "h"), True)
        assert c.get(("ssh", "h")) is True
        # persisted across instances
        c2 = cache_mod.Cache(cache_dir=str(tmp_path), ttl_s=1000)
        assert c2.get(("ssh", "h")) is True
        # expired
        c3 = cache_mod.Cache(cache_dir=str(tmp_path), ttl_s=0)
        assert c3.get(("ssh", "h")) is None

    def test_timeout(self):
        t = Timeout(0.0, "boom")
        time.sleep(0.01)
        with pytest.raises(TimeoutException, match="boom"):
            t.check()


class TestLocalLaunch:
    """End-to-end: run_command_on_hosts spawns N local workers with correct
    rank env and propagates failures (reference run/run.py:458-481 parity,
    minus mpirun)."""

    def test_two_local_workers_env(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(
            "import os\n"
            "out = os.path.join(os.environ['OUT'], "
            "'r' + os.environ['HVD_PROCESS_ID'])\n"
            "open(out, 'w').write('|'.join([\n"
            "    os.environ['HVD_NUM_PROC'], os.environ['HVD_LOCAL_RANK'],\n"
            "    os.environ['HVD_COORDINATOR_ADDR']]))\n")
        os.environ["OUT"] = str(tmp_path)
        try:
            rc = run_command_on_hosts(
                hosts.parse_hosts("localhost:2"),
                [sys.executable, str(script)],
                "127.0.0.1:12345", Settings())
        finally:
            del os.environ["OUT"]
        assert rc == 0
        assert (tmp_path / "r0").read_text() == "2|0|127.0.0.1:12345"
        assert (tmp_path / "r1").read_text() == "2|1|127.0.0.1:12345"

    def test_failure_propagates(self):
        rc = run_command_on_hosts(
            hosts.parse_hosts("localhost:2"),
            [sys.executable, "-c", "import sys; sys.exit(7)"],
            "127.0.0.1:1", Settings())
        assert rc == 7

    def test_hvdrun_cli_module(self, tmp_path):
        """The installed entry point parses and launches."""
        res = subprocess.run(
            [sys.executable, "-c",
             "from horovod_tpu.run.cli import main; main()",
             "-np", "1", sys.executable, "-c", "print('worker-ok')"],
            capture_output=True, text=True, timeout=120,
            cwd="/root/repo")
        assert res.returncode == 0, res.stderr

    def test_cli_exports_secret_to_workers(self, monkeypatch):
        """The per-job secret must reach every worker's env: the
        negotiated eager control plane derives its HMAC key from it
        (ops/negotiation.py control_key). Regression pin for the
        round-5 fix — without it, hvdrun jobs silently fell back to
        the strict same-order contract."""
        import signal

        import pytest as _pytest

        from horovod_tpu.run import cli, secret

        captured = {}

        def fake_run(host_list, command, coordinator_addr, settings,
                     output_dir=None, extra_env=None, cancel_event=None):
            captured["extra_env"] = extra_env
            return 0

        monkeypatch.setattr(cli, "run_command_on_hosts", fake_run)
        prev = signal.getsignal(signal.SIGTERM)
        try:
            with _pytest.raises(SystemExit) as e:
                cli.main(["-np", "1", "true"])
        finally:
            signal.signal(signal.SIGTERM, prev)  # main() installs one
        assert e.value.code == 0
        assert captured["extra_env"] is not None
        assert secret.HVD_SECRET_KEY in captured["extra_env"]

    def test_terminate_trees_kills_sigterm_ignoring_group(self, tmp_path):
        """terminate_trees must reach its SIGKILL pass promptly even
        when the process ignores SIGTERM (jax's preemption notifier
        swallows it) — the leak mode behind the round-5 elastic-drill
        fix."""
        import time as _time

        from horovod_tpu.run import exec_util

        script = tmp_path / "stubborn.py"
        script.write_text(
            "import signal, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "print('ready', flush=True)\n"
            "time.sleep(60)\n")
        procs = [exec_util.safe_execute(
            [sys.executable, str(script)], stdout=subprocess.PIPE)
            for _ in range(2)]
        for p in procs:
            assert p.stdout.readline().strip() == b"ready"
        t0 = _time.monotonic()
        exec_util.terminate_trees(procs, grace_s=0.5)
        dt = _time.monotonic() - t0
        for p in procs:
            assert p.poll() is not None, "stubborn worker survived"
        # one SHARED grace window, not one per proc
        assert dt < 5.0, dt
