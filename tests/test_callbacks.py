"""Callback tests — parity with the reference Keras callback suite
(_keras/callbacks.py; exercised in test/test_keras.py)."""

import numpy as np
import optax
import pytest


def _sgd_state(lr=0.1, momentum=0.9):
    import jax.numpy as jnp
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=lr,
                                             momentum=momentum)
    params = {"w": jnp.ones((2, 2))}
    return tx, params, tx.init(params)


class TestHyperparamPlumbing:
    def test_get_set_learning_rate(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state(lr=0.25)
        assert cb.get_hyperparam(opt_state, "learning_rate") == 0.25
        assert cb.set_hyperparam(opt_state, "learning_rate", 0.5)
        assert cb.get_hyperparam(opt_state, "learning_rate") == 0.5

    def test_nested_in_chain_and_multisteps(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu import callbacks as cb
        tx = optax.MultiSteps(
            optax.chain(optax.clip(1.0),
                        optax.inject_hyperparams(optax.sgd)(
                            learning_rate=0.1)), every_k_schedule=2)
        opt_state = tx.init({"w": jnp.ones(3)})
        assert cb.get_hyperparam(opt_state, "learning_rate") == pytest.approx(
            0.1)
        assert cb.set_hyperparam(opt_state, "learning_rate", 0.7)
        assert cb.get_hyperparam(opt_state, "learning_rate") == pytest.approx(
            0.7)

    def test_missing_returns_none(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state()
        assert cb.get_hyperparam(opt_state, "nope") is None
        assert not cb.set_hyperparam(opt_state, "nope", 1.0)


class TestBroadcastCallback:
    def test_broadcasts_on_train_begin(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu import callbacks as cb
        tx, params, opt_state = _sgd_state()
        loop = cb.LoopState(params=params, opt_state=opt_state)
        cbs = cb.CallbackList([cb.BroadcastGlobalVariablesCallback(0)], loop)
        cbs.on_train_begin()
        np.testing.assert_allclose(np.asarray(loop.params["w"]),
                                   np.ones((2, 2)))


class TestMetricAverage:
    def test_averages_logs(self, hvd):
        from horovod_tpu import callbacks as cb
        loop = cb.LoopState()
        cbs = cb.CallbackList([cb.MetricAverageCallback()], loop)
        logs = {"loss": 2.0, "acc": 0.5}
        cbs.on_epoch_end(0, logs)
        # single process: average over 1 participant = identity; types float
        assert logs["loss"] == pytest.approx(2.0)
        assert isinstance(logs["loss"], float)


class TestLRSchedule:
    def test_staircase_multiplier(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state(lr=0.1, momentum=0.9)
        loop = cb.LoopState(opt_state=opt_state)
        sched = cb.LearningRateScheduleCallback(
            multiplier=lambda e: 0.1 ** e, start_epoch=0,
            momentum_correction=False)
        cbs = cb.CallbackList([sched], loop)
        cbs.on_train_begin()
        cbs.on_epoch_begin(1)
        cbs.on_batch_begin(0)
        assert cb.get_hyperparam(opt_state, "learning_rate") == pytest.approx(
            0.1 * 0.1)

    def test_constant_multiplier_forces_staircase(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state(lr=1.0)
        loop = cb.LoopState(opt_state=opt_state)
        sched = cb.LearningRateScheduleCallback(multiplier=0.5,
                                                momentum_correction=False)
        cbs = cb.CallbackList([sched], loop)
        cbs.on_train_begin()
        cbs.on_epoch_begin(3)
        cbs.on_batch_begin(0)
        assert cb.get_hyperparam(opt_state, "learning_rate") == pytest.approx(
            0.5)

    def test_momentum_correction_and_restore(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state(lr=0.1, momentum=0.9)
        loop = cb.LoopState(opt_state=opt_state)
        sched = cb.LearningRateScheduleCallback(
            multiplier=lambda e: 2.0, momentum_correction=True)
        cbs = cb.CallbackList([sched], loop)
        cbs.on_train_begin()
        cbs.on_epoch_begin(0)
        cbs.on_batch_begin(0)
        # momentum scaled by new_lr/old_lr = 2.0 during the batch
        assert cb.get_hyperparam(opt_state, "momentum") == pytest.approx(1.8)
        cbs.on_batch_end(0)
        assert cb.get_hyperparam(opt_state, "momentum") == pytest.approx(0.9)

    def test_outside_epoch_range_no_change(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state(lr=0.1)
        loop = cb.LoopState(opt_state=opt_state)
        sched = cb.LearningRateScheduleCallback(
            multiplier=lambda e: 99.0, start_epoch=5,
            momentum_correction=False)
        cbs = cb.CallbackList([sched], loop)
        cbs.on_train_begin()
        cbs.on_epoch_begin(0)
        cbs.on_batch_begin(0)
        assert cb.get_hyperparam(opt_state, "learning_rate") == pytest.approx(
            0.1)

    def test_logs_lr_on_epoch_end(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state(lr=0.3)
        loop = cb.LoopState(opt_state=opt_state)
        sched = cb.LearningRateScheduleCallback(multiplier=1.0,
                                                momentum_correction=False)
        cbs = cb.CallbackList([sched], loop)
        cbs.on_train_begin()
        logs = {}
        cbs.on_epoch_end(0, logs)
        assert logs["lr"] == pytest.approx(0.3)


class TestWarmup:
    def test_warmup_curve(self, hvd):
        from horovod_tpu import callbacks as cb
        _, _, opt_state = _sgd_state(lr=0.8, momentum=0.9)
        loop = cb.LoopState(opt_state=opt_state, steps_per_epoch=10)
        warm = cb.LearningRateWarmupCallback(warmup_epochs=5,
                                             momentum_correction=False,
                                             steps_per_epoch=10)
        cbs = cb.CallbackList([warm], loop)
        cbs.on_train_begin()
        size = hvd.size()
        # first batch of epoch 0: epoch_frac = 0 + 0/10 (+1/10 adjustment)
        cbs.on_epoch_begin(0)
        cbs.on_batch_begin(0)
        e = 0.0 + 1.0 / 10
        expect = 0.8 / size * (e * (size - 1) / 5 + 1)
        assert cb.get_hyperparam(opt_state, "learning_rate") == pytest.approx(
            expect, rel=1e-5)
        # end of warmup reaches the full LR
        cbs.on_epoch_begin(4)
        cbs.on_batch_begin(9)
        e = 4 + 9 / 10 + 1 / 10
        expect = 0.8 / size * (e * (size - 1) / 5 + 1)
        assert cb.get_hyperparam(opt_state, "learning_rate") == pytest.approx(
            expect, rel=1e-5)
        assert expect == pytest.approx(0.8, rel=1e-5)

    def test_warmup_schedule_matches_callback(self, hvd):
        from horovod_tpu import callbacks as cb
        size = hvd.size()
        sched = cb.warmup_schedule(0.8, warmup_epochs=5, steps_per_epoch=10,
                                   size=size)
        # step 49 == last warmup step == full LR
        assert float(sched(49)) == pytest.approx(0.8, rel=1e-5)
        # after warmup stays at base
        assert float(sched(200)) == pytest.approx(0.8)
        # start ≈ base/size
        e = 1.0 / 10
        expect = 0.8 / size * (e * (size - 1) / 5 + 1)
        assert float(sched(0)) == pytest.approx(expect, rel=1e-5)


class TestFullLoopSmoke:
    def test_callbacks_in_training_loop(self, hvd):
        import jax
        import jax.numpy as jnp
        from horovod_tpu import callbacks as cb

        tx = hvd.DistributedOptimizer(
            optax.inject_hyperparams(optax.sgd)(learning_rate=0.1,
                                                momentum=0.9))
        params = {"w": jnp.ones((4,))}
        opt_state = tx.init(params)
        loop = cb.LoopState(params=params, opt_state=opt_state,
                            steps_per_epoch=2)
        cbs = cb.CallbackList(
            [cb.BroadcastGlobalVariablesCallback(0),
             cb.MetricAverageCallback(),
             cb.LearningRateWarmupCallback(warmup_epochs=2,
                                           steps_per_epoch=2)], loop)

        def loss_fn(p, x):
            return jnp.sum((p["w"] * x) ** 2)

        cbs.on_train_begin()
        x = jnp.arange(4.0)
        for epoch in range(3):
            cbs.on_epoch_begin(epoch)
            for batch in range(2):
                cbs.on_batch_begin(batch)
                grads = jax.grad(loss_fn)(loop.params, x)
                updates, loop.opt_state = tx.update(
                    grads, loop.opt_state, loop.params)
                loop.params = optax.apply_updates(loop.params, updates)
                cbs.on_batch_end(batch)
            logs = {"loss": float(loss_fn(loop.params, x))}
            cbs.on_epoch_end(epoch, logs)
        assert np.isfinite(logs["loss"])


class TestKerasFloatMomentumCorrection:
    def test_correction_reaches_compiled_fit(self, hvd):
        """Default Keras SGD stores momentum as a plain float, which a
        compiled train step bakes in at trace time. The schedule
        callback must rebuild it as a tracked Variable so momentum
        correction (m *= new_lr/old_lr, reference
        _keras/callbacks.py:70-146) actually changes the update.

        Hand-computed trajectory (w0=1, x=1, y=0, mse => g = 2w;
        SGD: m' = mom*m - lr*g; w += m'):
          epoch0 b0: lr 0.1 (ratio 1, corr no-op): m=-0.2,  w=0.8
          epoch1 b0: lr 0.2, corrected mom 1.8:
                     m = 1.8*(-0.2) - 0.2*1.6 = -0.68,     w=0.12
        Without correction (mom stays 0.9) w would be 0.3 — the assert
        distinguishes the two."""
        keras = pytest.importorskip("keras")
        from horovod_tpu.keras.callbacks import (
            LearningRateScheduleCallback)

        model = keras.Sequential([
            keras.layers.Input((1,)),
            keras.layers.Dense(1, use_bias=False,
                               kernel_initializer="ones")])
        opt = keras.optimizers.SGD(0.1, momentum=0.9)
        assert isinstance(opt.momentum, float)  # the problematic case
        model.compile(optimizer=opt, loss="mse")
        cb_ = LearningRateScheduleCallback(multiplier=lambda e: 2.0 ** e,
                                           momentum_correction=True)
        x = np.ones((1, 1), np.float32)
        y = np.zeros((1, 1), np.float32)
        model.fit(x, y, batch_size=1, epochs=2, verbose=0,
                  callbacks=[cb_])
        w = float(np.asarray(model.layers[0].kernel)[0, 0])
        assert w == pytest.approx(0.12, abs=1e-5)
        # restored to the uncorrected value after the adjusted batch
        assert float(opt.momentum) == pytest.approx(0.9, abs=1e-6)
        # the momentum wrapper must not break optimizer serialization
        # (it subclasses float): save + reload round-trips
        import os
        import tempfile
        path = os.path.join(tempfile.mkdtemp(), "m.keras")
        model.save(path)
        m2 = keras.saving.load_model(path)
        assert float(m2.optimizer.momentum) == pytest.approx(0.9,
                                                             abs=1e-6)
