"""Quantized wire codecs (ops/quantization.py + ops/compression.py):
block encode/decode round-trip bounds vs numpy, error-feedback
convergence on a toy quadratic, digest determinism for the divergence
sentinel, the codec registry contract, and the multi-process
codec-mismatch fail-loud drill."""

import hashlib

import numpy as np
import pytest

from horovod_tpu.run.launch import run

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _q():
    from horovod_tpu.ops import quantization
    return quantization


def _np_block_amax(x, block):
    return np.abs(x.reshape(-1, block)).max(axis=1)


class TestBlockRoundTrip:
    """encode/decode against independent numpy math."""

    def test_int8_error_bounded_by_half_scale(self):
        q = _q()
        rng = np.random.RandomState(0)
        x = (rng.randn(4096).astype(np.float32) *
             np.repeat(10.0 ** rng.randint(-3, 3, 16), 256))
        payload, scales = q.encode(x, 256, "int8")
        assert str(payload.dtype) == "int8"
        dec = np.asarray(q.decode(payload, scales, 256, x.shape[0]))
        # symmetric int8: worst case is half a quantization step per
        # element, scale = amax/127 per block
        step = _np_block_amax(x, 256) / 127.0
        bound = np.repeat(step / 2, 256) + 1e-7
        assert (np.abs(dec - x) <= bound).all()
        # scales match the numpy amax definition
        assert np.allclose(np.asarray(scales),
                           _np_block_amax(x, 256) / 127.0, rtol=1e-6)

    def test_fp8_error_bounded_relative(self):
        q = _q()
        if not q.HAS_FP8:
            pytest.skip("no float8_e4m3fn in this jax build")
        rng = np.random.RandomState(1)
        x = rng.randn(2048).astype(np.float32) * 4.0
        payload, scales = q.encode(x, 256, "fp8")
        assert "float8_e4m3" in str(payload.dtype)
        dec = np.asarray(q.decode(payload, scales, 256, x.shape[0]))
        # e4m3 has a 3-bit mantissa: relative error <= 2^-4 for normal
        # values, plus one subnormal quantum (scale covers it) near 0
        scale = np.repeat(_np_block_amax(x, 256) / 448.0, 256)
        bound = np.abs(x) * 2.0 ** -4 + scale + 1e-7
        assert (np.abs(dec - x) <= bound).all()

    def test_zero_blocks_and_pad_tail_decode_exactly(self):
        q = _q()
        x = np.zeros(300, np.float32)
        x[:10] = np.linspace(-1, 1, 10)
        payload, scales = q.encode(x, 256, "int8")
        # 300 pads to 512: the all-pad second block gets scale 0, no inf
        assert payload.shape[0] == 512
        assert np.asarray(scales)[1] >= 0.0
        dec = np.asarray(q.decode(payload, scales, 256, 300))
        assert dec.shape == (300,)
        assert (dec[10:] == 0.0).all()
        # explicit multiple (the two-phase collective's block * nproc)
        p2, _ = q.encode(x, 256, "int8", multiple=256 * 4)
        assert p2.shape[0] == 1024

    def test_bf16_input_roundtrips_through_f32_math(self):
        import jax.numpy as jnp
        q = _q()
        x = (np.random.RandomState(2).randn(512).astype(np.float32))
        xb = jnp.asarray(x, jnp.bfloat16)
        payload, scales = q.encode(xb, 256, "int8")
        dec = np.asarray(q.decode(payload, scales, 256, 512))
        step = _np_block_amax(np.asarray(xb, np.float32), 256) / 127.0
        assert (np.abs(dec - np.asarray(xb, np.float32))
                <= np.repeat(step / 2, 256) + 1e-6).all()


class TestDigestDeterminism:
    """The divergence sentinel compares per-bucket digests across
    ranks; the quantized path must produce bit-identical reduced
    buffers everywhere or every quantized step would false-positive."""

    def test_stacked_rows_bitwise_identical(self):
        q = _q()
        rng = np.random.RandomState(3)
        stacked = rng.randn(4, 2048).astype(np.float32)
        out, _ = q.stacked_wire_allreduce(stacked, 256, "int8", False,
                                          2048)
        rows = np.asarray(out)
        digests = {hashlib.sha256(rows[i].tobytes()).hexdigest()
                   for i in range(rows.shape[0])}
        assert len(digests) == 1

    def test_repeated_encode_is_deterministic(self):
        q = _q()
        x = np.random.RandomState(4).randn(1024).astype(np.float32)
        p1, s1 = q.encode(x, 128, "int8")
        p2, s2 = q.encode(x, 128, "int8")
        assert np.asarray(p1).tobytes() == np.asarray(p2).tobytes()
        assert np.asarray(s1).tobytes() == np.asarray(s2).tobytes()

    def test_stacked_sum_matches_numpy_within_bound(self):
        q = _q()
        rng = np.random.RandomState(5)
        stacked = rng.randn(4, 4096).astype(np.float32)
        out, dec = q.stacked_wire_allreduce(stacked, 256, "int8", True,
                                            4096)
        ref = stacked.mean(axis=0)
        amax = np.abs(ref).max()
        assert np.abs(np.asarray(out)[0] - ref).max() <= 0.02 * amax
        # the EF reference really is each row's own-wire decode
        assert np.abs(np.asarray(dec) - stacked).max() <= \
            np.abs(stacked).max() / 127.0


class TestErrorFeedback:
    def test_residual_is_what_the_encode_dropped(self):
        q = _q()
        x = np.random.RandomState(6).randn(512).astype(np.float32)
        ef = q.ErrorFeedback()
        comp = ef.compensate("t", x)  # no residual yet: identity
        assert comp is x
        p, s = q.encode(comp, 256, "int8")
        dec = q.decode(p, s, 256, 512)
        ef.update("t", comp, dec, 256)
        comp2 = np.asarray(ef.compensate("t", x))
        assert np.allclose(comp2, x + (x - np.asarray(dec)), atol=1e-6)
        # shape change resets (elastic resize)
        assert ef.compensate("t", np.zeros(8, np.float32)).shape == (8,)

    def test_toy_quadratic_converges_like_full_width(self):
        """GD on 0.5*||w - t||^2 with the gradient pushed through the
        quantized wire: with EF the loss trajectory must track the
        full-width one; without EF the bias accumulates."""
        q = _q()
        rng = np.random.RandomState(7)
        t = rng.randn(512).astype(np.float32)
        lr, steps, block = 0.2, 60, 64

        def train(mode):
            w = np.zeros(512, np.float32)
            ef = q.ErrorFeedback()
            for _ in range(steps):
                g = w - t
                if mode == "exact":
                    gq = g
                else:
                    comp = ef.compensate("w", g) if mode == "ef" else g
                    p, s = q.encode(np.asarray(comp, np.float32), block,
                                    "int8")
                    gq = np.asarray(q.decode(p, s, block, 512))
                    if mode == "ef":
                        ef.update("w", comp, gq, block)
                w = w - lr * gq
            return 0.5 * float(((w - t) ** 2).sum())

        exact, with_ef = train("exact"), train("ef")
        # quantized-with-EF matches full width within the numerics
        # tolerance (absolute: both losses are ~0 at this horizon)
        assert with_ef <= exact + 1e-3, (with_ef, exact)

    def test_residual_norm_gauge_exported(self):
        from horovod_tpu.utils import metrics as hvd_metrics
        q = _q()
        reg = hvd_metrics.get_registry()
        if not reg.enabled:
            pytest.skip("metrics registry disabled")
        x = np.random.RandomState(8).randn(256).astype(np.float32)
        ef = q.ErrorFeedback()
        p, s = q.encode(x, 64, "int8")
        ef.update("t", x, q.decode(p, s, 64, 256), 64, anchor="grad/t")
        snap = reg.snapshot()
        mets = snap[1]["metrics"] if isinstance(snap, tuple) else \
            snap["metrics"]
        vals = mets["hvd_ef_residual_norm"]["values"]
        assert any(v["labels"].get("tensor") == "grad/t" and
                   v["value"] > 0 for v in vals)


class TestCodecRegistry:
    def test_from_name_and_names(self):
        from horovod_tpu.ops.compression import Compression
        assert set(Compression.names()) >= {"none", "fp16", "bf16",
                                            "int8"}
        assert Compression.from_name(None) is Compression.none
        assert Compression.from_name("") is Compression.none
        assert Compression.from_name(" BF16 ") is Compression.bf16
        assert Compression.from_name("int8") is Compression.int8
        with pytest.raises(ValueError, match="unknown compression"):
            Compression.from_name("zstd")

    def test_every_codec_skips_non_float(self):
        import jax.numpy as jnp
        from horovod_tpu.ops.compression import Compression
        inputs = [np.arange(6, dtype=np.int32),
                  np.array([True, False, True]),
                  np.array([1 + 2j, 3 - 1j], np.complex64),
                  jnp.arange(4, dtype=jnp.int8),
                  7,
                  [1, 2, 3]]
        for name in Compression.names():
            codec = Compression.from_name(name)
            for x in inputs:
                out, ctx = codec.compress(x)
                restored = np.asarray(codec.decompress(out, ctx))
                assert np.array_equal(restored, np.asarray(x)), \
                    (name, x)

    def test_cast_codecs_narrow_then_restore(self):
        import jax.numpy as jnp
        from horovod_tpu.ops.compression import Compression
        x = np.linspace(-2, 2, 64, dtype=np.float32)
        for name, wire in (("fp16", jnp.float16), ("bf16", jnp.bfloat16)):
            codec = Compression.from_name(name)
            out, ctx = codec.compress(x)
            assert out.dtype == wire
            back = codec.decompress(out, ctx)
            assert back.dtype == np.float32
            assert np.abs(np.asarray(back) - x).max() < 0.02
        # already at wire width: no-op, ctx None
        xb = jnp.asarray(x, jnp.bfloat16)
        out, ctx = Compression.bf16.compress(xb)
        assert ctx is None and out is xb

    def test_quantized_codec_is_fake_quant_on_this_path(self):
        from horovod_tpu.ops.compression import Compression
        x = np.random.RandomState(9).randn(3, 100).astype(np.float32)
        out, ctx = Compression.int8.compress(x)
        assert ctx is None
        out = np.asarray(out)
        assert out.shape == x.shape and out.dtype == x.dtype
        assert 0 < np.abs(out - x).max() <= np.abs(x).max() / 127.0

    def test_select_codec_gates(self):
        from horovod_tpu.common.config import HorovodConfig
        q = _q()
        cfg = HorovodConfig(compression="int8", quant_min_bytes=1024)
        assert q.select_codec(cfg, "float32", 4096) == "int8"
        assert q.select_codec(cfg, "float32", 64) is None   # too small
        assert q.select_codec(cfg, "int32", 4096) is None   # not float
        assert q.select_codec(cfg, None, 4096) is None      # no dtype
        cfg2 = HorovodConfig(compression="bf16", quant_min_bytes=0)
        assert q.select_codec(cfg2, "float32", 4096) == "bf16"
        assert q.select_codec(cfg2, "bfloat16", 4096) is None  # no-op
        cfg3 = HorovodConfig()
        assert q.select_codec(cfg3, "float32", 4096) is None

    def test_config_fingerprint_covers_every_wire_knob(self):
        from horovod_tpu.common.config import HorovodConfig
        q = _q()
        base = HorovodConfig(compression="int8")
        fp = q.config_fingerprint(base)
        for other in (HorovodConfig(compression="fp8"),
                      HorovodConfig(compression="int8", quant_block=128),
                      HorovodConfig(compression="int8",
                                    quant_min_bytes=2048),
                      HorovodConfig(compression="int8", quant_ef=False)):
            assert q.config_fingerprint(other) != fp

    def test_encoded_nbytes_accounting(self):
        q = _q()
        # int8: pad(5000, 256)=5120 payload + 20 f32 scales
        assert q.encoded_nbytes(5000, "int8", 256) == 5120 + 20 * 4
        assert q.encoded_nbytes(5000, "bf16", 256) == 10000
        # the acceptance ratio: int8-vs-bf16 wire >= 1.8x
        n = 1 << 20
        assert (q.encoded_nbytes(n, "bf16", 256) /
                q.encoded_nbytes(n, "int8", 256)) >= 1.8


class TestEagerQuantizedPath:
    """End-to-end through hvd.allreduce with HVD_COMPRESSION set
    (single process: the stacked/replicated simulated wire)."""

    def test_allreduce_quantized_with_metrics(self):
        env = dict(_ENV, HVD_COMPRESSION="int8", HVD_QUANT_MIN_BYTES="0",
                   HVD_METRICS="1")

        def fn():
            import numpy as np
            import jax.numpy as jnp
            import horovod_tpu as hvd
            from horovod_tpu.utils import metrics as hvd_metrics
            hvd.init()
            x = np.random.RandomState(0).randn(
                hvd.size(), 5000).astype(np.float32)
            out1 = np.asarray(hvd.allreduce(jnp.asarray(x),
                                            average=False, name="g"))
            # second step exercises the EF residual on the same bucket
            out2 = np.asarray(hvd.allreduce(jnp.asarray(x),
                                            average=False, name="g"))
            ref = np.broadcast_to(x.sum(axis=0), x.shape)
            scale = np.abs(ref).max()
            err = max(np.abs(out1 - ref).max(), np.abs(out2 - ref).max())
            # int tensors stay exact through the codec gate
            z = np.arange(64, dtype=np.int32)
            zi = np.asarray(hvd.allreduce(jnp.asarray(z), average=False,
                                          name="zi"))
            snap = hvd_metrics.get_registry().snapshot()
            mets = snap[1]["metrics"] if isinstance(snap, tuple) else \
                snap["metrics"]
            wire = {v["labels"]["codec"]: v["value"] for v in
                    mets["hvd_wire_bytes_total"]["values"]}
            raw = {v["labels"]["codec"]: v["value"] for v in
                    mets["hvd_wire_raw_bytes_total"]["values"]}
            hvd.shutdown()
            return (float(err / scale), bool((zi == z).all()),
                    wire.get("int8", 0), raw.get("int8", 0))

        (rel_err, ints_exact, wire_b, raw_b), = run(fn, num_proc=1,
                                                    env=env)
        assert rel_err < 0.02
        assert ints_exact
        # encoded bytes crossed the accounting: ~4x smaller than raw
        assert 0 < wire_b < raw_b / 3

    def test_unknown_codec_name_fails_at_init(self):
        env = dict(_ENV, HVD_COMPRESSION="zstd")

        def fn():
            import contextlib
            import horovod_tpu as hvd
            try:
                hvd.init()
            except ValueError as e:
                return str(e)
            finally:
                with contextlib.suppress(Exception):
                    hvd.shutdown()
            return "no error"

        (out,) = run(fn, num_proc=1, env=env)
        assert "unknown compression codec" in out and "zstd" in out

    def test_codec_mismatch_fails_loudly_at_negotiation(self):
        """Acceptance: rank-asymmetric codec config must fail at
        negotiation (versioned plan field), never corrupt a sum."""
        env = dict(_ENV, HVD_QUANT_MIN_BYTES="0", HVD_NEGOTIATION="1")

        def fn():
            import os
            import jax.numpy as jnp
            rank = int(os.environ.get("HVD_PROCESS_ID", "0"))
            os.environ["HVD_COMPRESSION"] = \
                "int8" if rank == 0 else "none"
            import horovod_tpu as hvd
            from horovod_tpu.common.exceptions import MismatchError
            hvd.init()
            try:
                hvd.allreduce(jnp.ones(3000, jnp.float32), name="g")
                outcome = "no error"
            except MismatchError as e:
                outcome = str(e)
            hvd.shutdown()
            return outcome

        for outcome in run(fn, num_proc=2, env=env):
            assert "Mismatched wire-codec config" in outcome
            assert "int8" in outcome and "none" in outcome
