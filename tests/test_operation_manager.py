"""Backend dispatch (ops/operation_manager.py) — parity with the
reference's priority-ordered OperationManager (operation_manager.cc:32-80):
first backend whose Enabled() returns true executes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def test_default_is_xla(hvd):
    from horovod_tpu.ops import operation_manager as om
    mgr = om.get_operation_manager()
    cfg = hvd.common.state.global_state().config
    assert mgr._select("hvd", ["hvd"], cfg).name == "xla"


def test_ring_enabled_by_config(hvd):
    from horovod_tpu.ops import operation_manager as om
    cfg = hvd.common.state.global_state().config
    cfg.ring_allreduce = True
    try:
        mgr = om.get_operation_manager()
        assert mgr._select("hvd", ["hvd"], cfg).name == "ring"
        # tuple axes never take the ring path
        assert mgr._select(("slices", "chips"),
                           ["slices", "chips"], cfg).name == "xla"
    finally:
        cfg.ring_allreduce = False


def test_hierarchical_priority_over_ring(hvd):
    from horovod_tpu.ops import operation_manager as om
    cfg = hvd.common.state.global_state().config
    cfg.ring_allreduce = True
    cfg.hierarchical_allreduce = True
    try:
        mgr = om.get_operation_manager()
        # spanning reduction on a bound hierarchy → hierarchical wins
        assert mgr._select(("slices", "chips"),
                           ["slices", "chips"], cfg).name == "hierarchical"
        # single-axis reduction → hierarchical not applicable → ring
        assert mgr._select("chips", ["slices", "chips"], cfg).name == "ring"
    finally:
        cfg.ring_allreduce = False
        cfg.hierarchical_allreduce = False


def test_ring_backend_through_allreduce_traced(hvd):
    """HOROVOD_RING_ALLREDUCE routes hvd.allreduce inside shard_map through
    the explicit ring; result must equal the XLA psum path."""
    from horovod_tpu.ops import collective_ops as cops
    n = hvd.size()
    x = np.random.RandomState(0).randn(n, 6).astype(np.float32)

    def f(t):
        return cops.allreduce_traced(t, average=True, axis_name="hvd")

    run = lambda: jax.jit(jax.shard_map(
        f, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd")))(x)
    want = np.asarray(run())

    cfg = hvd.common.state.global_state().config
    cfg.ring_allreduce = True
    try:
        got = np.asarray(run())
    finally:
        cfg.ring_allreduce = False
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_hierarchical_backend_through_allreduce_traced(hvd):
    """A ('slices','chips') spanning allreduce with the hierarchical flag on
    equals the flat two-axis psum."""
    from horovod_tpu.ops import collective_ops as cops
    from horovod_tpu.parallel import mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)
    x = np.arange(8.0 * 3, dtype=np.float32).reshape(8, 3)

    def f(t):
        return cops.allreduce_traced(t, average=True,
                                     axis_name=("slices", "chips"))

    def run():
        return jax.jit(jax.shard_map(
            f, mesh=m, in_specs=P(("slices", "chips")),
            out_specs=P(("slices", "chips"))))(x)

    want = np.asarray(run())    # xla path
    cfg = hvd.common.state.global_state().config
    cfg.hierarchical_allreduce = True
    try:
        got = np.asarray(run())
    finally:
        cfg.hierarchical_allreduce = False
    np.testing.assert_allclose(got, want, rtol=1e-6)
    np.testing.assert_allclose(
        got, np.tile(x.mean(0, keepdims=True), (8, 1)), rtol=1e-6)


def test_resolve_axis_none_prefers_bound_hierarchy(hvd):
    """The dispatch gap the docstring promise left open: a traced
    context that binds BOTH hierarchy axes but passes axis_name=None
    used to resolve to a single axis, so the hierarchical backend never
    matched. With the flag on, the allreduce entry points now resolve
    None to the spanning pair and the two-level backend wins."""
    from horovod_tpu.ops import collective_ops as cops
    from horovod_tpu.parallel import mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)
    x = np.arange(8.0, dtype=np.float32)
    cfg = hvd.common.state.global_state().config
    seen = {}

    def f(t):
        seen["axis"] = cops.resolve_axis(None, prefer_hierarchy=True)
        return cops.allreduce_traced(t, average=False)

    def run():
        return jax.jit(jax.shard_map(
            f, mesh=m, in_specs=P(("slices", "chips")),
            out_specs=P(("slices", "chips"))))(x)

    run()
    # flag off: None resolves to one bound axis, exactly as before
    assert isinstance(seen["axis"], str)
    cfg.hierarchical_allreduce = True
    try:
        got = np.asarray(run())
        assert isinstance(seen["axis"], tuple)
        assert set(seen["axis"]) == {"slices", "chips"}
        mgr = __import__("horovod_tpu.ops.operation_manager",
                         fromlist=["om"]).get_operation_manager()
        assert mgr._select(seen["axis"], ["slices", "chips"],
                           cfg).name == "hierarchical"
    finally:
        cfg.hierarchical_allreduce = False
    # and the spanning reduction really reduced over the whole world
    np.testing.assert_allclose(got, np.full(8, x.sum()), rtol=1e-6)


def test_hierarchical_selection_emits_reduce_scatter(hvd):
    """Structural proof of dispatch: with the flag on, the jaxpr of an
    axis_name=None allreduce under a two-axis mesh contains the
    two-level schedule's reduce_scatter; with it off, it does not."""
    from horovod_tpu.ops import collective_ops as cops
    from horovod_tpu.parallel import mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)
    x = np.arange(8.0, dtype=np.float32)

    def f(t):
        return cops.allreduce_traced(t, average=False)

    def jaxpr_text():
        return str(jax.make_jaxpr(jax.shard_map(
            f, mesh=m, in_specs=P(("slices", "chips")),
            out_specs=P(("slices", "chips"))))(x))

    cfg = hvd.common.state.global_state().config
    assert "reduce_scatter" not in jaxpr_text()
    cfg.hierarchical_allreduce = True
    try:
        assert "reduce_scatter" in jaxpr_text()
    finally:
        cfg.hierarchical_allreduce = False


def test_env_knob_parsed(hvd, monkeypatch):
    from horovod_tpu.common.config import HorovodConfig
    monkeypatch.setenv("HOROVOD_RING_ALLREDUCE", "1")
    assert HorovodConfig.from_env().ring_allreduce
    monkeypatch.delenv("HOROVOD_RING_ALLREDUCE")
    monkeypatch.setenv("HVD_RING_ALLREDUCE", "1")
    assert HorovodConfig.from_env().ring_allreduce
