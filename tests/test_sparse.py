"""Sparse-gradient (IndexedSlices) tests — parity with the reference's
IndexedSlices→allgather allreduce (tensorflow/__init__.py:62-73) and
sparse_as_dense densification (_keras/__init__.py:39-46)."""

import numpy as np
import pytest


def _traced(hvd, fn, *args, in_specs=None, out_specs=None):
    import jax
    from jax.sharding import PartitionSpec as P
    mesh = hvd.mesh()
    in_specs = in_specs if in_specs is not None else P("hvd")
    out_specs = out_specs if out_specs is not None else P("hvd")
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs))(*args)


class TestIndexedSlices:
    def test_pytree_roundtrip(self, hvd):
        import jax
        import jax.numpy as jnp
        s = hvd.IndexedSlices(jnp.ones((2, 3)), jnp.array([0, 4]), (10, 3))
        leaves, treedef = jax.tree_util.tree_flatten(s)
        assert len(leaves) == 2
        s2 = jax.tree_util.tree_unflatten(treedef, leaves)
        assert s2.dense_shape == (10, 3)

    def test_to_dense_accumulates_duplicates(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu.ops import sparse
        s = hvd.IndexedSlices(jnp.ones((3, 2)), jnp.array([1, 1, 4]), (6, 2))
        d = sparse.to_dense(s)
        expect = np.zeros((6, 2))
        expect[1] = 2.0
        expect[4] = 1.0
        np.testing.assert_allclose(np.asarray(d), expect)

    def test_from_dense(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu.ops import sparse
        d = jnp.arange(12.0).reshape(6, 2)
        s = sparse.from_dense(d, [2, 5])
        np.testing.assert_allclose(np.asarray(s.values),
                                   np.asarray(d)[[2, 5]])
        assert s.dense_shape == (6, 2)


class TestSparseAllreduce:
    def test_eager_single_process_identity(self, hvd):
        # single process eagerly = single-rank horovod: allreduce is the
        # identity (same semantics as the dense eager replicated path).
        import jax.numpy as jnp
        from horovod_tpu.ops import sparse
        s = hvd.IndexedSlices(jnp.ones((2, 3)), jnp.array([1, 3]), (5, 3))
        out = hvd.sparse_allreduce(s, average=True)
        dense = sparse.to_dense(out)
        expect = np.zeros((5, 3))
        expect[1] = 1.0
        expect[3] = 1.0
        np.testing.assert_allclose(np.asarray(dense), expect, rtol=1e-6)

    def test_traced_matches_dense_allreduce(self, hvd):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.ops import sparse

        # worker i contributes row i with value i (rank-dependent data)
        vals = jnp.arange(8.0).reshape(8, 1, 1) * jnp.ones((8, 1, 4))
        idxs = jnp.arange(8, dtype=jnp.int32).reshape(8, 1)

        def fn(v, i):
            s = hvd.IndexedSlices(v[0], i[0], (8, 4))
            out = hvd.sparse_allreduce(s, average=False)
            return sparse.to_dense(out)[None]

        dense = _traced(hvd, fn, vals, idxs,
                        in_specs=(P("hvd"), P("hvd")), out_specs=P("hvd"))
        # every worker's block is the union: row i == i
        blocks = np.asarray(dense).reshape(8, 8, 4)
        expect = np.tile(np.arange(8.0)[:, None], (1, 4))
        for b in blocks:
            np.testing.assert_allclose(b, expect)

    def test_allreduce_dispatches_indexed_slices(self, hvd):
        import jax.numpy as jnp
        s = hvd.IndexedSlices(jnp.ones((1, 2)), jnp.array([0]), (4, 2))
        out = hvd.allreduce(s, average=False)
        assert isinstance(out, hvd.IndexedSlices)
        assert out.dense_shape == (4, 2)

    def test_sparse_rejects_min_max(self, hvd):
        import jax.numpy as jnp
        s = hvd.IndexedSlices(jnp.ones((1, 2)), jnp.array([0]), (4, 2))
        with pytest.raises(ValueError, match="sum/average"):
            hvd.allreduce(s, op="min")

    def test_grouped_allreduce_routes_sparse(self, hvd):
        # indices must never be summed as dense tensors
        import jax.numpy as jnp
        tree = {
            "embed": hvd.IndexedSlices(jnp.ones((2, 3)),
                                       jnp.array([1, 3]), (5, 3)),
            "w": jnp.full((2, 2), 3.0),
        }
        out = hvd.grouped_allreduce(tree, average=False)
        assert isinstance(out["embed"], hvd.IndexedSlices)
        assert out["embed"].indices.dtype == tree["embed"].indices.dtype
        np.testing.assert_array_equal(np.asarray(out["embed"].indices),
                                      [1, 3])
        np.testing.assert_allclose(np.asarray(out["w"]),
                                   np.full((2, 2), 3.0))

    def test_sparse_fp16_compression(self, hvd):
        import jax.numpy as jnp
        s = hvd.IndexedSlices(jnp.ones((2, 3), jnp.float32),
                              jnp.array([0, 1]), (4, 3))
        out = hvd.allreduce(s, average=False,
                            compression=hvd.Compression.fp16)
        assert out.values.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out.values), np.ones((2, 3)))


class TestSparseGradientTree:
    def test_mixed_tree_allreduce(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu import optim
        grads = {
            "embed": hvd.IndexedSlices(jnp.ones((2, 3)), jnp.array([0, 1]),
                                       (4, 3)),
            "w": jnp.full((2, 2), 2.0),
        }
        out = optim.allreduce_gradients(grads, average=False)
        assert isinstance(out["embed"], hvd.IndexedSlices)
        assert out["embed"].dense_shape == (4, 3)
        # single-process eager: allreduce over 1 participant = identity
        np.testing.assert_allclose(np.asarray(out["w"]), np.full((2, 2), 2.0))

    def test_distributed_optimizer_densifies_sparse(self, hvd):
        # IndexedSlices must never reach the inner optax transform
        import jax.numpy as jnp
        import optax
        tx = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
        params = {"embed": jnp.ones((4, 3))}
        opt_state = tx.init(params)
        grads = {"embed": hvd.IndexedSlices(jnp.ones((2, 3)),
                                            jnp.array([0, 2]), (4, 3))}
        updates, opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        assert not isinstance(new_params["embed"], hvd.IndexedSlices)
        got = np.asarray(new_params["embed"])
        np.testing.assert_allclose(got[0], 1.0 - 0.1)  # touched rows moved
        np.testing.assert_allclose(got[1], 1.0)        # untouched intact

    def test_eager_op_sum_not_averaged(self, hvd):
        out = hvd.allreduce(np.arange(8.0).reshape(8, 1), op="sum")
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))
        with pytest.raises(NotImplementedError):
            hvd.allreduce(np.ones((2, 2)), op="min")

    def test_eager_nnz_equal_to_world_size(self, hvd):
        # nnz == device count must not trip the eager core's stacked-array
        # heuristic (values would be reshaped, 1-D indices would crash)
        import jax.numpy as jnp
        from horovod_tpu.ops import sparse
        n = hvd.size()
        s = hvd.IndexedSlices(jnp.ones((n, 3)),
                              jnp.arange(n, dtype=jnp.int32), (2 * n, 3))
        out = hvd.sparse_allreduce(s, average=True)
        assert out.values.shape == (n, 3)
        assert out.indices.shape == (n,)
        dense = sparse.to_dense(out)
        np.testing.assert_allclose(np.asarray(dense[:n]), np.ones((n, 3)))

    def test_multisteps_accumulates_sparse(self, hvd):
        # backward_passes_per_step > 1 densifies before the accumulator
        import jax.numpy as jnp
        import optax
        tx = hvd.DistributedOptimizer(optax.sgd(0.1),
                                      backward_passes_per_step=2)
        params = {"embed": jnp.zeros((4, 3))}
        opt_state = tx.init(params)
        grads = {"embed": hvd.IndexedSlices(jnp.ones((2, 3)),
                                            jnp.array([0, 2]), (4, 3))}
        for _ in range(2):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        got = np.asarray(params["embed"])
        np.testing.assert_allclose(got[0], -0.1, rtol=1e-6)  # mean of 2
        np.testing.assert_allclose(got[1], 0.0)

    def test_sparse_as_dense(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu import optim
        grads = {
            "embed": hvd.IndexedSlices(jnp.ones((2, 3)), jnp.array([0, 0]),
                                       (4, 3)),
        }
        out = optim.allreduce_gradients(grads, average=False,
                                        sparse_as_dense=True)
        assert not isinstance(out["embed"], hvd.IndexedSlices)
        expect = np.zeros((4, 3))
        expect[0] = 2.0  # duplicates accumulate; 1 participant eager
        np.testing.assert_allclose(np.asarray(out["embed"]), expect)
