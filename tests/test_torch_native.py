"""Torch on the native collective plane (torch/native.py over
libhvd_plane.so — the factored TCP-ring plane of _native/src/plane.h;
role of the reference's C torch binding, torch/mpi_ops_v2.cc:52-130).

Multi-process cases spawn real workers via run.launch.run: plane
bootstrap, ring collectives on torch storage (GIL released), fallback
and error surfaces.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from horovod_tpu.run.launch import run  # noqa: E402

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _plane_available():
    from horovod_tpu.torch import native
    return native.available()


class TestTorchNativePlane:
    def test_hook_driven_optimizer_rides_native_plane(self):
        """The DistributedOptimizer's post-accumulate-grad hooks must go
        through the plane (no eager-core crossing) and still converge to
        the same averaged-gradient update."""
        def fn():
            import os
            import numpy as np
            import torch
            import horovod_tpu.torch as hvd
            from horovod_tpu.torch import native

            hvd.init()
            if not native.available():
                return "unavailable"
            r = int(os.environ["HVD_PROCESS_ID"])
            model = torch.nn.Linear(4, 1, bias=False)
            with torch.no_grad():
                model.weight.fill_(1.0)
            opt = hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=1.0),
                named_parameters=model.named_parameters())
            core_calls = []
            from horovod_tpu.torch import mpi_ops as tops
            orig = tops._core.allreduce_async

            def spy(t, **kw):
                core_calls.append(kw.get("name"))
                return orig(t, **kw)

            tops._core.allreduce_async = spy
            x = torch.full((2, 4), float(r + 1))
            loss = model(x).sum()
            loss.backward()
            opt.step()
            tops._core.allreduce_async = orig
            w = model.weight.detach().numpy().copy()
            plane_up = native._state["plane_up"]
            hvd.shutdown()
            return w.tolist(), len(core_calls), bool(plane_up)

        results = run(fn, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_plane.so unavailable in workers")
        for w, n_core, plane_up in results:
            # grad = x summed over batch = 2*(r+1) per input feature;
            # averaged over ranks: (2 + 4)/2 = 3; w = 1 - 3
            np.testing.assert_allclose(np.asarray(w), -2.0)
            assert plane_up, "native plane did not come up"
            assert n_core == 0, "gradients crossed into the eager core"

    def test_matches_bridge_path_numerics(self):
        """Native route and the numpy bridge must produce identical
        results for the same submissions (fp32 and bf16)."""
        def fn():
            import os
            import torch
            import horovod_tpu.torch as hvd
            from horovod_tpu.torch import native

            hvd.init()
            if (os.environ.get("HVD_TORCH_NATIVE") != "0"
                    and not native.available()):
                return "unavailable"
            r = int(os.environ["HVD_PROCESS_ID"])
            res = {}
            t = torch.arange(64, dtype=torch.float32) * (r + 1)
            res["f32"] = hvd.allreduce(t, average=True,
                                       name="ab.f32").tolist()
            b = torch.arange(16, dtype=torch.bfloat16) * (r + 1)
            res["bf16"] = hvd.allreduce(
                b, average=False, name="ab.bf16").float().tolist()
            res["native"] = bool(native._state["plane_up"])
            hvd.shutdown()
            return res

        native_env = dict(_ENV)
        bridge_env = dict(_ENV, HVD_TORCH_NATIVE="0")
        nat = run(fn, num_proc=2, env=native_env)
        if nat[0] == "unavailable":
            pytest.skip("libhvd_plane.so unavailable in workers")
        bri = run(fn, num_proc=2, env=bridge_env)
        assert nat[0]["native"] and not bri[0]["native"]
        for k in ("f32", "bf16"):
            assert nat[0][k] == bri[0][k] == nat[1][k] == bri[1][k]

    def test_allgatherv_native(self):
        """Variable-first-dim allgather over the plane: each rank
        contributes a different number of rows; every rank gets the
        concatenation in rank order (the reference's allgatherv,
        mpi_operations.cc:86-173)."""
        def fn():
            import os
            import torch
            import horovod_tpu.torch as hvd
            from horovod_tpu.torch import native

            hvd.init()
            if not native.available():
                return "unavailable"
            r = int(os.environ["HVD_PROCESS_ID"])
            # rank 0: 1 row, rank 1: 2 rows — rows carry the rank
            t = torch.full((r + 1, 3), float(r), dtype=torch.float32)
            out = hvd.allgather(t, name="agv")
            core_free = not any(
                isinstance(k, int) for k in
                __import__("horovod_tpu.torch.mpi_ops",
                           fromlist=["_handle_map"])._handle_map)
            sc = hvd.allgather(torch.tensor(float(r)), name="agv.scalar")
            hvd.shutdown()
            return (out.tolist(), list(out.shape), sc.tolist(),
                    bool(native._state["plane_up"]), core_free)

        results = run(fn, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_plane.so unavailable in workers")
        want = [[0.0, 0.0, 0.0], [1.0, 1.0, 1.0], [1.0, 1.0, 1.0]]
        for out, shape, sc, plane_up, core_free in results:
            assert out == want
            assert shape == [3, 3]
            assert sc == [0.0, 1.0]
            assert plane_up
            # the gathers really rode the plane: no eager-core handles
            assert core_free, "allgather fell back to the numpy bridge"

    def test_shape_mismatch_errors(self):
        """Same name, same byte count, different shapes across ranks:
        the shape digest must reject it (plane.h note_ready)."""
        def fn():
            import os
            import torch
            import horovod_tpu.torch as hvd
            from horovod_tpu.torch import native

            hvd.init()
            if not native.available():
                return "unavailable"
            r = int(os.environ["HVD_PROCESS_ID"])
            got = None
            try:
                t = torch.zeros((2, 3) if r == 0 else (3, 2))
                hvd.allreduce_(t, name="clash.shape")
            except RuntimeError as e:
                got = "mismatched" in str(e)
            # the plane survives for a well-formed collective
            ok = hvd.allreduce(torch.ones(4), average=False,
                               name="after.clash")
            hvd.shutdown()
            return got, float(ok[0])

        results = run(fn, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_plane.so unavailable in workers")
        for got, after in results:
            assert got, "shape mismatch did not raise"
            assert after == 2.0

    def test_poll_completes_without_releasing_handle(self):
        """hvd.poll on a native handle reports completion truthfully and
        leaves the handle joinable (reference poll/synchronize contract,
        torch/mpi_ops.py:406-438)."""
        def fn():
            import time
            import torch
            import horovod_tpu.torch as hvd
            from horovod_tpu.torch import native

            hvd.init()
            if not native.available():
                return "unavailable"
            h = hvd.allreduce_async_(torch.ones(64), average=False,
                                     name="poll.t")
            deadline = time.monotonic() + 30
            while not hvd.poll(h):
                if time.monotonic() > deadline:
                    hvd.shutdown()
                    return "poll-timeout"
                time.sleep(0.005)
            out = hvd.synchronize(h)  # still joinable after poll=True
            hvd.shutdown()
            return float(out[0])

        results = run(fn, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_plane.so unavailable in workers")
        assert results == [2.0, 2.0], results

    def test_disabled_env_uses_bridge(self):
        def fn():
            import torch
            import horovod_tpu.torch as hvd
            from horovod_tpu.torch import native

            hvd.init()
            out = hvd.allreduce(torch.ones(8), average=False, name="br")
            up = native._state["plane_up"]
            hvd.shutdown()
            return float(out[0]), bool(up)

        results = run(fn, num_proc=2,
                      env=dict(_ENV, HVD_TORCH_NATIVE="0"))
        for v, up in results:
            assert v == 2.0 and not up
