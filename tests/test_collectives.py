"""Collective op tests — the analogue of the reference's op matrix
(test/test_torch.py, test/test_tensorflow.py): every collective, eager and
traced, with rank-dependent deterministic data and exact-value asserts
(SURVEY.md §4 'tensor = rank * ones' pattern)."""

import numpy as np
import pytest


def _traced(hvd, fn, *args, in_specs=None, out_specs=None):
    import jax
    from jax.sharding import PartitionSpec as P
    mesh = hvd.mesh()
    in_specs = in_specs if in_specs is not None else P("hvd")
    out_specs = out_specs if out_specs is not None else P("hvd")
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs))(*args)


# ---------------------------------------------------------------------------
# traced (in-jit) path
# ---------------------------------------------------------------------------

class TestTraced:
    def test_allreduce_sum(self, hvd):
        import jax.numpy as jnp
        x = jnp.arange(8.0).reshape(8, 1)  # worker i holds value i
        out = _traced(hvd, lambda s: hvd.allreduce(s, average=False), x)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))

    def test_allreduce_average(self, hvd):
        import jax.numpy as jnp
        x = jnp.arange(8.0).reshape(8, 1)
        out = _traced(hvd, lambda s: hvd.allreduce(s, average=True), x)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.5))

    def test_allreduce_fp16_compression(self, hvd):
        import jax.numpy as jnp
        x = jnp.ones((8, 4), jnp.float32)
        out = _traced(
            hvd, lambda s: hvd.allreduce(s, average=False,
                                         compression=hvd.Compression.fp16), x)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))

    def test_allreduce_min_max(self, hvd):
        import jax.numpy as jnp
        x = jnp.arange(8.0).reshape(8, 1)
        out = _traced(hvd, lambda s: hvd.allreduce(s, op="min"), x)
        np.testing.assert_allclose(np.asarray(out), np.zeros((8, 1)))
        out = _traced(hvd, lambda s: hvd.allreduce(s, op="max"), x)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 7.0))

    def test_allgather(self, hvd):
        import jax.numpy as jnp
        x = jnp.arange(8.0).reshape(8, 1)  # worker i holds [i]
        out = _traced(hvd, hvd.allgather, x)
        # each worker gets the concat of all workers' rows
        np.testing.assert_allclose(np.asarray(out),
                                   np.tile(np.arange(8.0)[:, None], (8, 1))
                                   .reshape(64, 1)[:64])

    def test_broadcast(self, hvd):
        import jax.numpy as jnp
        x = jnp.arange(8.0).reshape(8, 1)
        out = _traced(hvd, lambda s: hvd.broadcast(s, root_rank=3), x)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.0))

    def test_reducescatter(self, hvd):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        # every worker holds [0..7]; reduce-scatter gives worker i 8*i
        x = jnp.tile(jnp.arange(8.0), (8, 1))
        out = _traced(hvd, lambda s: hvd.reducescatter(s[0]), x,
                      in_specs=P("hvd"), out_specs=P("hvd"))
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 8)

    def test_alltoall(self, hvd):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        # worker i holds row of 8 values 10*i + [0..7]
        x = jnp.arange(64.0).reshape(8, 8)
        out = _traced(hvd, lambda s: hvd.alltoall(s, split_axis=1,
                                                  concat_axis=0),
                      x, in_specs=P("hvd"), out_specs=P("hvd"))
        # worker j receives column j of every worker (shape [8,1] each);
        # global result is the transpose, flattened to (64, 1)
        np.testing.assert_allclose(
            np.asarray(out).reshape(8, 8),
            np.arange(64.0).reshape(8, 8).T)

    def test_grouped_allreduce_fused(self, hvd):
        import jax.numpy as jnp
        xs = {"a": jnp.arange(8.0).reshape(8, 1),
              "b": jnp.ones((8, 3), jnp.float32)}
        out = _traced(
            hvd,
            lambda a, b: hvd.grouped_allreduce({"a": a, "b": b},
                                               average=False),
            xs["a"], xs["b"],
            in_specs=None or __import__("jax").sharding.PartitionSpec("hvd"),
            out_specs=__import__("jax").sharding.PartitionSpec("hvd"))
        np.testing.assert_allclose(np.asarray(out["a"]),
                                   np.full((8, 1), 28.0))
        np.testing.assert_allclose(np.asarray(out["b"]),
                                   np.full((8, 3), 8.0))


# ---------------------------------------------------------------------------
# eager path (coordination core)
# ---------------------------------------------------------------------------

class TestEager:
    def test_allreduce_stacked_sum(self, hvd):
        x = np.arange(8.0).reshape(8, 1) * np.ones((8, 3))
        out = hvd.allreduce(x, average=False)
        np.testing.assert_allclose(np.asarray(out),
                                   np.full((8, 3), 28.0) * np.ones((8, 3)))

    def test_allreduce_stacked_average(self, hvd):
        x = np.arange(8.0).reshape(8, 1) * np.ones((8, 3))
        out = hvd.allreduce(x, average=True)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 3), 3.5))

    def test_allreduce_replicated_single_process(self, hvd):
        # 1 process → allreduce over 1 participant = identity (like a
        # single-rank horovod run)
        x = np.full((3, 3), 4.0)
        np.testing.assert_allclose(np.asarray(hvd.allreduce(x)), x)

    def test_allreduce_async_poll_synchronize(self, hvd):
        x = np.arange(8.0).reshape(8, 1)
        h = hvd.allreduce_async(x, average=False)
        out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))

    def test_duplicate_name_error(self, hvd):
        import horovod_tpu
        x = np.zeros((8, 1))
        coord = horovod_tpu.common.state.global_state().coordinator
        coord._paused = True  # hold the flush so both enqueues overlap
        try:
            hvd.allreduce_async(x, name="dup")
            with pytest.raises(hvd.DuplicateNameError):
                hvd.allreduce_async(x, name="dup")
        finally:
            coord._paused = False

    def test_allgather_stacked(self, hvd):
        x = np.arange(8.0).reshape(8, 1, 1) + np.zeros((8, 1, 2))
        out = hvd.allgather(x)
        assert out.shape == (8, 2)
        np.testing.assert_allclose(np.asarray(out)[:, 0], np.arange(8.0))

    def test_allgather_variable_size(self, hvd):
        # reference test_horovod_allgather_variable_size
        # (test/test_tensorflow.py:563): ranks contribute different dim-0.
        tensors = [np.full((i + 1, 2), float(i)) for i in range(8)]
        out = hvd.allgather(tensors)
        assert out.shape == (sum(i + 1 for i in range(8)), 2)
        row = 0
        for i in range(8):
            np.testing.assert_allclose(np.asarray(out)[row:row + i + 1],
                                       np.full((i + 1, 2), float(i)))
            row += i + 1

    def test_allgather_type_mismatch_error(self, hvd):
        tensors = [np.zeros((2, 2), np.float32), np.zeros((2, 2), np.int32)]
        with pytest.raises(hvd.MismatchError):
            hvd.allgather(tensors)

    def test_allgather_shape_mismatch_error(self, hvd):
        tensors = [np.zeros((2, 2)), np.zeros((2, 3))]
        with pytest.raises(hvd.MismatchError):
            hvd.allgather(tensors)

    def test_broadcast_stacked(self, hvd):
        x = np.arange(8.0).reshape(8, 1) * np.ones((8, 4))
        out = hvd.broadcast(x, root_rank=5)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 5.0))

    def test_broadcast_replicated_identity(self, hvd):
        x = np.full((2, 2), 7.0)
        np.testing.assert_allclose(np.asarray(hvd.broadcast(x, root_rank=0)),
                                   x)

    def test_reducescatter_stacked(self, hvd):
        # worker i holds row i = i * ones(16); each gets its 1/8 shard of
        # the sum (= 28 * ones(2))
        x = np.arange(8.0)[:, None] * np.ones((8, 16))
        out = np.asarray(hvd.reducescatter(x))
        assert out.shape == (8, 2)
        np.testing.assert_allclose(out, np.full((8, 2), 28.0))
        avg = np.asarray(hvd.reducescatter(x, average=True))
        np.testing.assert_allclose(avg, np.full((8, 2), 3.5))

    def test_reducescatter_indivisible_raises(self, hvd):
        with pytest.raises(hvd.MismatchError, match="divisible"):
            hvd.reducescatter(np.ones((8, 15)))

    def test_alltoall_stacked(self, hvd):
        # worker j sends chunk i (value 10*j + i) to worker i; worker i
        # ends with [10*0+i, 10*1+i, ..., 10*7+i]
        world = 8
        x = np.zeros((world, world), np.float32)
        for j in range(world):
            for i in range(world):
                x[j, i] = 10 * j + i
        out = np.asarray(hvd.alltoall(x))
        assert out.shape == (world, world)
        for i in range(world):
            np.testing.assert_allclose(out[i], 10 * np.arange(world) + i)

    def test_eager_fusion_batches_small_tensors(self, hvd):
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        coord._paused = True
        try:
            handles = [hvd.allreduce_async(
                np.full((8, 2), float(i)), average=False, name=f"fuse{i}")
                for i in range(4)]
            coord._paused = False
            coord.flush()
            outs = [hvd.synchronize(h) for h in handles]
        finally:
            coord._paused = False
        for i, out in enumerate(outs):
            np.testing.assert_allclose(np.asarray(out),
                                       np.full((8, 2), 8.0 * i))

    def test_plan_cache_hit_on_repeat(self, hvd):
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        coord.plan_cache.clear()
        hits0 = coord.plan_cache.hits
        x = np.ones((8, 2))
        for _ in range(3):
            coord._paused = True
            h = hvd.allreduce_async(x, average=False, name="cached")
            coord._paused = False
            coord.flush()
            hvd.synchronize(h)
        assert coord.plan_cache.hits >= hits0 + 2

    def test_shutdown_error_after_shutdown(self, hvd):
        hvd.shutdown()
        with pytest.raises((hvd.NotInitializedError, hvd.ShutdownError)):
            hvd.allreduce(np.zeros((8, 1)))
        hvd.init()  # restore for fixture teardown
