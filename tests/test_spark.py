"""Spark integration (reference test/test_spark.py patterns:
test_happy_run on local[2], missing-context errors). pyspark is not in
the image, so a minimal stand-in implementing the exact surface the
integration uses (SparkContext._active_spark_context, parallelize →
barrier → mapPartitions → collect, BarrierTaskContext.get/allGather/
partitionId) runs the barrier stage inline — with one partition the
shipped fn executes for real, hvd.init() and all."""

import sys
import types as _types

import numpy as np
import pytest


def _install_fake_pyspark():
    if "pyspark" in sys.modules:
        return sys.modules["pyspark"]

    class BarrierTaskContext:
        _current = None

        def __init__(self, pid, addresses):
            self._pid = pid
            self._addresses = addresses

        @classmethod
        def get(cls):
            return cls._current

        def partitionId(self):
            return self._pid

        def allGather(self, message):
            self._addresses.append(message)
            return self._addresses

    class _BarrierRDD:
        def __init__(self, items, n_parts):
            self._items = items
            self._n = n_parts

        def mapPartitions(self, f):
            self._f = f
            return self

        def collect(self):
            results, addresses = [], []
            for pid in range(self._n):
                BarrierTaskContext._current = BarrierTaskContext(
                    pid, addresses)
                try:
                    results.extend(self._f(iter([self._items[pid]])))
                finally:
                    BarrierTaskContext._current = None
            return results

    class _RDD(_BarrierRDD):
        def barrier(self):
            return self

    class SparkContext:
        _active_spark_context = None

        def __init__(self, default_parallelism=2):
            self.defaultParallelism = default_parallelism

        def parallelize(self, data, n_parts):
            return _RDD(list(data), n_parts)

    mod = _types.ModuleType("pyspark")
    mod.SparkContext = SparkContext
    mod.BarrierTaskContext = BarrierTaskContext
    sys.modules["pyspark"] = mod
    return mod


@pytest.fixture
def pyspark():
    return _install_fake_pyspark()


@pytest.fixture
def shvd(pyspark):
    import os
    import horovod_tpu.spark as shvd_mod
    yield shvd_mod
    pyspark.SparkContext._active_spark_context = None
    # inline "tasks" export worker env into this test process — scrub it
    from horovod_tpu.run import secret
    for k in ("HVD_COORDINATOR_ADDR", "HVD_NUM_PROC", "HVD_PROCESS_ID",
              secret.HVD_SECRET_KEY):
        os.environ.pop(k, None)


class TestSparkRun:
    def test_requires_active_context(self, shvd):
        with pytest.raises(Exception, match="active SparkContext"):
            shvd.run(lambda: 0, num_proc=1)

    def test_happy_run_single_task(self, pyspark, shvd, monkeypatch):
        """reference test_spark.py:51-69 test_happy_run: fn runs on the
        tasks, per-rank results come back in rank order. One partition →
        the whole path (barrier allGather rendezvous, HVD_* env, fn
        execution with a real hvd.init) runs inline."""
        monkeypatch.setattr(pyspark.SparkContext,
                            "_active_spark_context",
                            pyspark.SparkContext())
        # fn runs in THIS process: the env the barrier task exports must
        # not leak jax.distributed bootstrap into our single-process jax
        monkeypatch.delenv("HVD_COORDINATOR_ADDR", raising=False)

        def fn(mult):
            import os
            from horovod_tpu.run import secret
            assert os.environ["HVD_NUM_PROC"] == "1"
            assert secret.HVD_SECRET_KEY in os.environ
            # single task: init without the multi-process bootstrap
            os.environ.pop("HVD_COORDINATOR_ADDR", None)
            import horovod_tpu as hvd
            import numpy as np
            hvd.init()
            out = float(np.asarray(
                hvd.allreduce(np.full((3,), 2.0), average=False))[0])
            hvd.shutdown()
            return out * mult

        assert shvd.run(fn, args=(10,), num_proc=1) == [20.0]

    def test_default_parallelism_inferred(self, pyspark, shvd,
                                          monkeypatch, capsys):
        monkeypatch.setattr(pyspark.SparkContext,
                            "_active_spark_context",
                            pyspark.SparkContext(default_parallelism=3))
        ranks = shvd.run(lambda: 0, num_proc=3, verbose=1)
        assert ranks == [0, 0, 0]
        assert "Running 3 processes" in capsys.readouterr().out

    def test_worker_env_matches_hvdrun_surface(self, shvd):
        from horovod_tpu.run import secret
        env = shvd.worker_env(2, 4, "10.0.0.1:1234", "a2V5",
                              extra_env={"FOO": "1"})
        assert env["HVD_COORDINATOR_ADDR"] == "10.0.0.1:1234"
        assert env["HVD_NUM_PROC"] == "4"
        assert env["HVD_PROCESS_ID"] == "2"
        assert env[secret.HVD_SECRET_KEY] == "a2V5"
        assert env["FOO"] == "1"
