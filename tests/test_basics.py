"""Identity/init API tests (reference test/test_torch.py rank/size checks and
horovod/common/basics.py semantics)."""

import pytest


def test_not_initialized_errors():
    import horovod_tpu as hvd_mod
    if hvd_mod.is_initialized():
        hvd_mod.shutdown()
    with pytest.raises(hvd_mod.NotInitializedError):
        hvd_mod.size()
    with pytest.raises(hvd_mod.NotInitializedError):
        hvd_mod.rank()


def test_init_size_rank(hvd):
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.rank() == 0          # first device of this (only) process
    assert hvd.local_rank() == 0
    assert hvd.process_rank() == 0
    assert hvd.process_count() == 1
    assert hvd.mpi_threads_supported() is True


def test_double_init_is_noop(hvd):
    hvd.init()
    assert hvd.size() == 8


def test_rank_inside_shard_map(hvd):
    """rank() inside shard_map is the per-device index (SPMD identity)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = hvd.mesh()

    def f(x):
        return x + hvd.rank()

    out = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("hvd"),
                                out_specs=P("hvd")))(jnp.zeros(8))
    assert list(out) == list(range(8))


class TestMpirunCompat:
    def test_mpi_env_without_rendezvous_derives_one(self, monkeypatch):
        """mpirun-launched jobs (reference OMPI_COMM_WORLD_* env,
        test/common.py:25-57) no longer need HVD_COORDINATOR_ADDR:
        init() routes through the automatic filesystem rendezvous
        (run/mpi.py) with the detected world. End-to-end coverage:
        tests/test_mpi_compat.py."""
        import horovod_tpu as hvd_mod
        from horovod_tpu.run import mpi as mpi_compat
        monkeypatch.delenv("HVD_COORDINATOR_ADDR", raising=False)
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "1")
        seen = {}

        def fake_rendezvous(size, rank, timeout_s=60.0):
            seen.update(size=size, rank=rank)
            raise RuntimeError("stop before jax.distributed")

        monkeypatch.setattr(mpi_compat, "auto_rendezvous", fake_rendezvous)
        with pytest.raises(RuntimeError, match="stop before"):
            hvd_mod.init()
        assert seen == {"size": 4, "rank": 1}

    def test_mpi_ranks_honored_with_rendezvous(self, monkeypatch):
        """With the rendezvous exported, OMPI ranks feed
        jax.distributed.initialize."""
        import horovod_tpu.mpi_ops as mpi_ops
        for k in ("HVD_NUM_PROC", "HVD_PROCESS_ID", "PMI_SIZE",
                  "PMI_RANK"):
            monkeypatch.delenv(k, raising=False)
        monkeypatch.setenv("HVD_COORDINATOR_ADDR", "127.0.0.1:43210")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
        seen = {}

        def fake_initialize(coordinator_address, num_processes, process_id):
            seen.update(addr=coordinator_address, n=num_processes,
                        pid=process_id)
            raise RuntimeError("stop before real bootstrap")

        monkeypatch.setattr(mpi_ops.jax.distributed, "initialize",
                            fake_initialize)
        with pytest.raises(RuntimeError, match="stop before"):
            mpi_ops.init()
        assert seen == {"addr": "127.0.0.1:43210", "n": 4, "pid": 3}


def test_hold_cycle_nests_and_restores(hvd):
    """coordinator.hold_cycle(): burst collectives land in one fused
    cycle; nested holds must not release the outer hold early, and the
    prior paused state is restored on exit."""
    import numpy as np
    import horovod_tpu
    coord = horovod_tpu.common.state.global_state().coordinator
    assert coord._paused is False
    with coord.hold_cycle():
        assert coord._paused is True
        with coord.hold_cycle():
            assert coord._paused is True
        # inner exit must NOT release the outer hold
        assert coord._paused is True
        h = hvd.allreduce_async(np.ones(4, np.float32), average=False,
                                name="hold.t")
    assert coord._paused is False
    out = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), np.ones(4))
