"""Identity/init API tests (reference test/test_torch.py rank/size checks and
horovod/common/basics.py semantics)."""

import pytest


def test_not_initialized_errors():
    import horovod_tpu as hvd_mod
    if hvd_mod.is_initialized():
        hvd_mod.shutdown()
    with pytest.raises(hvd_mod.NotInitializedError):
        hvd_mod.size()
    with pytest.raises(hvd_mod.NotInitializedError):
        hvd_mod.rank()


def test_init_size_rank(hvd):
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.rank() == 0          # first device of this (only) process
    assert hvd.local_rank() == 0
    assert hvd.process_rank() == 0
    assert hvd.process_count() == 1
    assert hvd.mpi_threads_supported() is True


def test_double_init_is_noop(hvd):
    hvd.init()
    assert hvd.size() == 8


def test_rank_inside_shard_map(hvd):
    """rank() inside shard_map is the per-device index (SPMD identity)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = hvd.mesh()

    def f(x):
        return x + hvd.rank()

    out = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("hvd"),
                                out_specs=P("hvd")))(jnp.zeros(8))
    assert list(out) == list(range(8))
