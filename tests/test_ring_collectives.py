"""Explicit ring collectives (parallel/ring_collectives.py) — the
hand-written equivalent of the reference's ring reduce-scatter/all-gather
data plane, validated against XLA's built-in psum/all_gather."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _shard_call(hvd, fn, x, out_specs=P("hvd")):
    m = hvd.mesh()
    return jax.jit(jax.shard_map(
        fn, mesh=m, in_specs=P("hvd"), out_specs=out_specs))(x)


def test_ring_all_reduce_matches_psum(hvd):
    from horovod_tpu.parallel import ring_collectives as rc
    n = hvd.size()
    x = np.arange(n * 7, dtype=np.float32).reshape(n, 7) + 1.0

    out = _shard_call(hvd, lambda t: rc.ring_all_reduce(t, "hvd"), x)
    want = np.tile(x.sum(axis=0, keepdims=True), (n, 1))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


def test_ring_all_reduce_average_odd_size(hvd):
    from horovod_tpu.parallel import ring_collectives as rc
    n = hvd.size()
    # 13 elements per shard: not divisible by n → exercises padding.
    x = np.random.RandomState(0).randn(n, 13).astype(np.float32)

    out = _shard_call(
        hvd, lambda t: rc.ring_all_reduce(t, "hvd", average=True), x)
    want = np.tile(x.mean(axis=0, keepdims=True), (n, 1))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


def test_ring_reduce_scatter_ownership(hvd):
    """Chip i must own fully-reduced chunk i (so AG composes)."""
    from horovod_tpu.parallel import ring_collectives as rc
    n = hvd.size()
    per = 2 * n  # divisible: no padding
    x = np.random.RandomState(1).randn(n, per).astype(np.float32)

    out = _shard_call(
        hvd, lambda t: rc.ring_reduce_scatter(t, "hvd")[None, :], x)
    # out is [n, per/n] stacked over chips; chip i's row = chunk i of sum
    total = x.sum(axis=0)
    want = total.reshape(n, per // n)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


def test_ring_all_gather_roundtrip(hvd):
    from horovod_tpu.parallel import ring_collectives as rc
    n = hvd.size()
    x = np.random.RandomState(2).randn(n, 5).astype(np.float32)

    def fn(t):
        return rc.ring_all_gather(t[0], "hvd")

    out = _shard_call(hvd, fn, x, out_specs=P("hvd", None))
    # every chip reconstructs the full rank-ordered table
    want = np.tile(x.reshape(1, n, 5), (n, 1, 1)).reshape(n * n, 5)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


def test_ring_all_reduce_multidim_bf16(hvd):
    from horovod_tpu.parallel import ring_collectives as rc
    n = hvd.size()
    x = (np.random.RandomState(3).randn(n, 3, 4, 5) * 0.1)

    def fn(t):
        return rc.ring_all_reduce(t.astype(jnp.bfloat16), "hvd")

    out = _shard_call(hvd, fn, x.astype(np.float32))
    want = np.tile(x.sum(axis=0, keepdims=True), (n, 1, 1, 1))
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32).reshape(n, 3, 4, 5), want,
        rtol=0.1, atol=0.1)


def test_ring_overlapped_applies_fn_once(hvd):
    from horovod_tpu.parallel import ring_collectives as rc
    n = hvd.size()
    x = np.random.RandomState(4).randn(n, 9).astype(np.float32)

    out = _shard_call(
        hvd,
        lambda t: rc.ring_all_reduce_overlapped(
            t, lambda c: 2.0 * c, "hvd", average=True),
        x)
    want = np.tile(2.0 * x.mean(axis=0, keepdims=True), (n, 1))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)
