"""Pipeline-parallel tests: the gpipe schedule must be numerically identical
to serial layer application (forward AND backward), and the full transformer
pipeline step must match the unpipelined model's loss."""

import numpy as np
import pytest


@pytest.fixture
def mesh24(hvd):
    """dp=2 × pp=4 mesh over the 8 CPU devices."""
    from horovod_tpu.parallel import mesh as mesh_mod
    return mesh_mod.build_mesh(dp=2, pp=4)


class TestGpipePrimitive:
    def test_matches_serial_forward(self, hvd, mesh24):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.parallel import pipeline as pl

        rng = np.random.RandomState(0)
        # 4 stages, each an affine map; stacked params sharded over pp
        W = jnp.asarray(rng.randn(4, 3, 3), jnp.float32)
        x = jnp.asarray(rng.randn(6, 2, 3), jnp.float32)  # [M=6, mb=2, 3]

        def per_rank(W_local, x_all):
            def stage_fn(a):
                return jnp.tanh(a @ W_local[0])
            out = pl.gpipe(stage_fn, x_all, axis_name="pp")
            return pl.last_stage_value(out, "pp")

        out = jax.jit(jax.shard_map(
            per_rank, mesh=mesh24, in_specs=(P("pp"), P()),
            out_specs=P()))(W, x)

        expect = x
        for i in range(4):
            expect = jnp.tanh(expect @ W[i])
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-6)

    def test_matches_serial_gradient(self, hvd, mesh24):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.parallel import pipeline as pl

        rng = np.random.RandomState(1)
        W = jnp.asarray(rng.randn(4, 3, 3), jnp.float32)
        x = jnp.asarray(rng.randn(4, 2, 3), jnp.float32)

        def pipe_loss(W_local, x_all):
            def stage_fn(a):
                return jnp.tanh(a @ W_local[0])
            out = pl.gpipe(stage_fn, x_all, axis_name="pp")
            return jnp.sum(pl.last_stage_value(out, "pp") ** 2)

        def per_rank(W_local, x_all):
            return jax.grad(pipe_loss)(W_local, x_all)

        grads = jax.jit(jax.shard_map(
            per_rank, mesh=mesh24, in_specs=(P("pp"), P()),
            out_specs=P("pp")))(W, x)

        def serial_loss(W_all):
            a = x
            for i in range(4):
                a = jnp.tanh(a @ W_all[i])
            return jnp.sum(a ** 2)

        expect = jax.grad(serial_loss)(W)
        np.testing.assert_allclose(np.asarray(grads), np.asarray(expect),
                                   rtol=2e-4, atol=1e-5)


class TestTransformerPipeline:
    def _setup(self, mesh, num_micro=2):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.parallel import pipeline as pl

        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)  # 2 layers → pp=2
        model = tr.TransformerLM(cfg)
        rng = jax.random.PRNGKey(0)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 33)),
            jnp.int32)
        params = model.init(rng, tokens[:, :-1])["params"]
        pparams = pl.stack_pipeline_params(params, cfg.num_layers)
        tx = optax.sgd(0.05)
        step, pshard, bshard = pl.make_pipeline_step(
            cfg, tx, mesh, num_micro, pparams)
        pparams = jax.tree_util.tree_map(jax.device_put, pparams, pshard)
        opt_state = tx.init(pparams)
        tokens = jax.device_put(tokens, bshard)
        return cfg, model, params, pparams, tx, opt_state, tokens, step

    def test_loss_matches_unpipelined(self, hvd):
        import jax
        import jax.numpy as jnp
        from horovod_tpu.parallel import mesh as mesh_mod
        from horovod_tpu import trainer
        mesh = mesh_mod.build_mesh(dp=4, pp=2)
        cfg, model, params, pparams, tx, opt_state, tokens, step = \
            self._setup(mesh)
        _, _, loss = step(pparams, opt_state, tokens)
        logits = model.apply({"params": params},
                             np.asarray(tokens)[:, :-1])
        expect = trainer.softmax_cross_entropy(
            logits, np.asarray(tokens)[:, 1:])
        np.testing.assert_allclose(float(loss), float(expect), rtol=1e-4)

    def test_step_update_matches_unpipelined(self, hvd):
        """One pipeline step must produce the SAME parameter update as the
        unpipelined single-device step — guards against grad overcounting
        from shard_map's automatic cotangent psum (dp× on the layer stack,
        dp·pp× on the replicated embed/head/norm)."""
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu.parallel import mesh as mesh_mod
        from horovod_tpu.parallel import pipeline as pl
        from horovod_tpu import trainer
        mesh = mesh_mod.build_mesh(dp=4, pp=2)
        cfg, model, params, pparams, tx, opt_state, tokens, step = \
            self._setup(mesh)
        p1, _, _ = step(pparams, opt_state, tokens)

        def loss_fn(p, toks):
            logits = model.apply({"params": p}, toks[:, :-1])
            return trainer.softmax_cross_entropy(logits, toks[:, 1:])

        toks = jnp.asarray(np.asarray(tokens))
        g = jax.grad(loss_fn)(params, toks)
        updates, _ = tx.update(g, tx.init(params), params)
        ref = pl.stack_pipeline_params(optax.apply_updates(params, updates),
                                       cfg.num_layers)
        for (ka, a), (kb, b) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(p1),
                       key=lambda kv: str(kv[0])),
                sorted(jax.tree_util.tree_leaves_with_path(ref),
                       key=lambda kv: str(kv[0]))):
            assert str(ka) == str(kb)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-5,
                                       err_msg=str(ka))

    def test_training_reduces_loss(self, hvd):
        from horovod_tpu.parallel import mesh as mesh_mod
        mesh = mesh_mod.build_mesh(dp=4, pp=2)
        cfg, model, params, pparams, tx, opt_state, tokens, step = \
            self._setup(mesh)
        losses = []
        for _ in range(8):
            pparams, opt_state, loss = step(pparams, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_stack_unstack_roundtrip(self, hvd):
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.parallel import pipeline as pl
        cfg = tr.TransformerConfig.tiny()
        model, params = tr.init_params(cfg, jax.random.PRNGKey(0))
        pparams = pl.stack_pipeline_params(params, cfg.num_layers)
        back = pl.unstack_pipeline_params(pparams, cfg.num_layers)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            params, back)

    def test_rejects_indivisible_layers(self, hvd):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.parallel import mesh as mesh_mod
        from horovod_tpu.parallel import pipeline as pl
        mesh = mesh_mod.build_mesh(dp=2, pp=4)
        cfg = tr.TransformerConfig.tiny()  # 2 layers, pp=4 → error
        model, params = tr.init_params(cfg, jax.random.PRNGKey(0))
        pparams = pl.stack_pipeline_params(params, cfg.num_layers)
        with pytest.raises(ValueError, match="divisible"):
            pl.make_pipeline_step(cfg, optax.sgd(0.1), mesh, 2, pparams)


class TestPipelineWithTensorParallel:
    """The 3-axis composition (VERDICT r2 item 3): pipeline stages whose
    kernels are ALSO Megatron-sharded over 'tp'. shard_map is manual over
    (dp, pp) only, tp stays a GSPMD axis — numerics must match the
    single-device model exactly, not just stay finite."""

    def test_dp2_pp2_tp2_update_matches_unpipelined(self, hvd):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.parallel import mesh as mesh_mod
        from horovod_tpu.parallel import pipeline as pl
        from horovod_tpu import trainer

        mesh = mesh_mod.build_mesh(dp=2, pp=2, tp=2)
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)  # 2 layers
        model = tr.TransformerLM(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 33)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
        pparams = pl.stack_pipeline_params(params, cfg.num_layers)
        tx = optax.sgd(0.05)
        step, pshard, bshard = pl.make_pipeline_step(
            cfg, tx, mesh, num_microbatches=2, pparams=pparams)
        # placement really is tp-sharded (not a silent all-replicated)
        qkv_spec = pshard["layers"]["attn"]["qkv"]["kernel"].spec
        assert "tp" in tuple(qkv_spec), qkv_spec
        pparams = jax.tree_util.tree_map(jax.device_put, pparams, pshard)
        opt_state = tx.init(pparams)
        tokens_sharded = jax.device_put(tokens, bshard)

        p1, _, loss = step(pparams, opt_state, tokens_sharded)

        def loss_fn(p, toks):
            logits = model.apply({"params": p}, toks[:, :-1])
            return trainer.softmax_cross_entropy(logits, toks[:, 1:])

        expect_loss = loss_fn(params, tokens)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-4)
        g = jax.grad(loss_fn)(params, tokens)
        updates, _ = tx.update(g, tx.init(params), params)
        ref = pl.stack_pipeline_params(
            optax.apply_updates(params, updates), cfg.num_layers)
        for (ka, a), (kb, b) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(p1),
                       key=lambda kv: str(kv[0])),
                sorted(jax.tree_util.tree_leaves_with_path(ref),
                       key=lambda kv: str(kv[0]))):
            assert str(ka) == str(kb)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-5,
                                       err_msg=str(ka))


class TestPipelineWithSequenceParallel:
    """The 4-axis composition (round 4): pipeline stages whose attention
    runs blockwise over the 'sp' ring (tokens sp-replicated, each member
    slicing its global-position chunk post-shift) while kernels stay
    Megatron-sharded over 'tp'. Numerics must match the single-device
    model exactly, not just stay finite."""

    @pytest.mark.parametrize("impl", ["ring", "ulysses", "ring_flash"])
    def test_pp2_tp2_sp2_update_matches_unpipelined(self, hvd, impl):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.parallel import mesh as mesh_mod
        from horovod_tpu.parallel import pipeline as pl
        from horovod_tpu import trainer

        mesh = mesh_mod.build_mesh(dp=1, pp=2, tp=2, sp=2)
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                        attention_impl=impl)
        model = tr.TransformerLM(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(4).randint(0, cfg.vocab_size, (4, 65)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(4), tokens[:, :-1])["params"]
        pparams = pl.stack_pipeline_params(params, cfg.num_layers)
        tx = optax.sgd(0.05)
        step, pshard, bshard = pl.make_pipeline_step(
            cfg, tx, mesh, num_microbatches=2, pparams=pparams)
        assert "tp" in tuple(
            pshard["layers"]["attn"]["qkv"]["kernel"].spec)
        pparams = jax.tree_util.tree_map(jax.device_put, pparams, pshard)
        opt_state = tx.init(pparams)
        tokens_sharded = jax.device_put(tokens, bshard)

        p1, _, loss = step(pparams, opt_state, tokens_sharded)

        def loss_fn(p, toks):
            # unsharded reference: these impls with the whole sequence
            # local run plain full/flash attention
            logits = model.apply({"params": p}, toks[:, :-1])
            return trainer.softmax_cross_entropy(logits, toks[:, 1:])

        expect_loss = loss_fn(params, tokens)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-4)
        g = jax.grad(loss_fn)(params, tokens)
        updates, _ = tx.update(g, tx.init(params), params)
        ref = pl.stack_pipeline_params(
            optax.apply_updates(params, updates), cfg.num_layers)
        for (ka, a), (kb, b) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(p1),
                       key=lambda kv: str(kv[0])),
                sorted(jax.tree_util.tree_leaves_with_path(ref),
                       key=lambda kv: str(kv[0]))):
            assert str(ka) == str(kb)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-5,
                                       err_msg=str(ka))

    def test_full_attention_leaves_sp_replicated(self, hvd):
        """attention_impl='full' on an sp>1 mesh keeps the pre-round-4
        behavior: the sequence stays whole (sp merely replicated), and
        the step still matches the unpipelined model."""
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.parallel import mesh as mesh_mod
        from horovod_tpu.parallel import pipeline as pl
        from horovod_tpu import trainer

        mesh = mesh_mod.build_mesh(dp=2, pp=2, sp=2)
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)
        model = tr.TransformerLM(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(5).randint(0, cfg.vocab_size, (4, 33)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(5), tokens[:, :-1])["params"]
        pparams = pl.stack_pipeline_params(params, cfg.num_layers)
        tx = optax.sgd(0.05)
        step, pshard, bshard = pl.make_pipeline_step(
            cfg, tx, mesh, num_microbatches=2, pparams=pparams)
        pparams = jax.tree_util.tree_map(jax.device_put, pparams, pshard)
        _, _, loss = step(pparams, tx.init(pparams),
                          jax.device_put(tokens, bshard))

        def loss_fn(p, toks):
            logits = model.apply({"params": p}, toks[:, :-1])
            return trainer.softmax_cross_entropy(logits, toks[:, 1:])

        np.testing.assert_allclose(float(loss),
                                   float(loss_fn(params, tokens)),
                                   rtol=1e-4)
