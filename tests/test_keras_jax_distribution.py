"""Keras-on-JAX distributed training via hvd.keras.use_jax_distribution():
the framework's answer for the backend where DistributedOptimizer cannot
intercept apply_gradients (it runs inside Keras's jit step). Runs in a
subprocess so KERAS_BACKEND=jax and the 8-device CPU mesh are set before
keras/jax import."""

import numpy as np
import pytest

pytest.importorskip("keras")

from horovod_tpu.run.launch import run  # noqa: E402

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "KERAS_BACKEND": "jax",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def test_fit_data_parallel_over_8_device_mesh():
    def worker():
        import jax
        import numpy as np
        import keras
        import horovod_tpu.keras as hvd

        hvd.init()
        dist = hvd.use_jax_distribution()
        n_devices = len(jax.devices())

        rng = np.random.RandomState(0)
        true_w = rng.randn(6, 1).astype(np.float32)
        x = rng.randn(512, 6).astype(np.float32)
        y = x @ true_w

        model = keras.Sequential(
            [keras.layers.Input((6,)), keras.layers.Dense(1)])
        model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
        hist = model.fit(x, y, batch_size=64, epochs=30, verbose=0)
        losses = hist.history["loss"]
        learned = np.asarray(model.layers[-1].kernel).ravel()
        hvd.shutdown()
        return {
            "n_devices": n_devices,
            "dist_set": keras.distribution.distribution() is dist,
            "first": float(losses[0]),
            "last": float(losses[-1]),
            "w_err": float(np.abs(learned - true_w.ravel()).max()),
        }

    rep = run(worker, num_proc=1, env=_ENV)[0]
    assert rep["n_devices"] == 8
    assert rep["dist_set"]
    assert rep["last"] < 1e-3 < rep["first"]
    assert rep["w_err"] < 0.05


def test_tf_backend_raises():
    """On the TF backend jax_distribution must refuse (the TF story is
    DistributedOptimizer)."""
    keras = pytest.importorskip("keras")
    if keras.backend.backend() != "tensorflow":
        pytest.skip("suite not running the TF backend")
    import horovod_tpu.keras as hvd
    with pytest.raises(ValueError, match="JAX backend"):
        hvd.jax_distribution()


def test_mesh_device_order_is_used():
    def worker():
        import jax
        import keras
        import horovod_tpu.keras as hvd
        from horovod_tpu.parallel import mesh as mesh_mod

        hvd.init()
        m = mesh_mod.build_mesh(dp=len(jax.devices()))
        dist = hvd.jax_distribution(mesh=m)
        hvd.shutdown()
        # DataParallel over exactly the mesh's devices, in mesh order
        got = [d.id for d in dist.device_mesh.devices.flat]
        want = [d.id for d in m.devices.flat]
        return got == want and len(got) == 8

    assert run(worker, num_proc=1, env=_ENV)[0]
