"""Torch frontend: collectives on torch tensors, DistributedOptimizer
hooks, parameter/optimizer-state broadcast (reference test_torch.py
patterns — single-process here, so process-level collectives are identity;
the mechanics of handles, hooks and in-place copies are what's under
test)."""

import numpy as np
import pytest
import torch


@pytest.fixture
def thvd(hvd):
    import horovod_tpu.torch as thvd_mod
    return thvd_mod


class TestTorchOps:
    def test_allreduce_identity_single_process(self, thvd):
        x = torch.arange(6, dtype=torch.float32).reshape(2, 3)
        out = thvd.allreduce(x, average=True)
        assert torch.is_tensor(out)
        np.testing.assert_allclose(out.numpy(), x.numpy())

    def test_allreduce_inplace(self, thvd):
        x = torch.ones(4) * 3
        out = thvd.allreduce_(x, average=False)
        assert out is x
        np.testing.assert_allclose(x.numpy(), 3 * np.ones(4))

    def test_allreduce_fp16_compression(self, thvd):
        x = torch.randn(8)
        out = thvd.allreduce(x, average=True,
                             compression=thvd.Compression.fp16)
        assert out.dtype == torch.float32
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-2)

    def test_async_poll_synchronize(self, thvd):
        x = torch.full((3,), 2.0)
        h = thvd.allreduce_async(x, average=False)
        out = thvd.synchronize(h)
        np.testing.assert_allclose(out.numpy(), 2 * np.ones(3))

    def test_broadcast_inplace(self, thvd):
        x = torch.randn(5)
        want = x.clone()
        out = thvd.broadcast_(x, root_rank=0)
        assert out is x
        np.testing.assert_allclose(x.numpy(), want.numpy())

    def test_allgather(self, thvd):
        x = torch.arange(4, dtype=torch.float32).reshape(2, 2)
        out = thvd.allgather(x)
        assert out.shape[0] == 2 * thvd.process_count()

    def test_size_rank_are_process_level(self, thvd):
        assert thvd.size() == thvd.process_count()
        assert thvd.rank() == thvd.process_rank()

    def test_rejects_non_tensor(self, thvd):
        with pytest.raises(ValueError, match="torch.Tensor"):
            thvd.allreduce(np.ones(3))

    def test_broadcast_root_out_of_range_raises_on_any_route(self, thvd):
        # route-independent error surface: the check runs before the
        # native/bridge route split, so an out-of-range root can never
        # reach the plane's ring recv (where no rank would act as root)
        with pytest.raises(ValueError, match="root_rank"):
            thvd.broadcast(torch.ones(3), root_rank=thvd.size())
        with pytest.raises(ValueError, match="root_rank"):
            thvd.broadcast_(torch.ones(3), root_rank=-1)

    def test_allreduce_bfloat16(self, thvd):
        # numpy has no bf16; the bridge rides fp32 and restores the dtype
        x = torch.randn(6, dtype=torch.bfloat16)
        out = thvd.allreduce(x, average=True)
        assert out.dtype == torch.bfloat16
        np.testing.assert_allclose(out.float().numpy(), x.float().numpy())

    def test_stale_handle_raises_descriptive_error(self, thvd):
        x = torch.ones(3)
        h = thvd.allreduce_async(x, average=False)
        thvd.synchronize(h)
        with pytest.raises(ValueError, match="already been synchronized"):
            thvd.synchronize(h)

    def test_async_snapshots_input(self, thvd):
        # the enqueued value must be captured at submit time: mutating the
        # tensor while the collective is in flight must not race
        x = torch.full((4,), 7.0)
        h = thvd.allreduce_async(x, average=False)
        x.zero_()
        out = thvd.synchronize(h)
        np.testing.assert_allclose(out.numpy(), 7 * np.ones(4))


class TestTorchDistributedOptimizer:
    def _model(self):
        torch.manual_seed(0)
        return torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                                   torch.nn.Linear(8, 1))

    def test_training_converges(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters())
        thvd.broadcast_parameters(model.state_dict(), root_rank=0)
        torch.manual_seed(1)
        X = torch.randn(64, 4)
        w = torch.tensor([[1.0], [-2.0], [0.5], [0.0]])
        Y = X @ w
        losses = []
        for _ in range(60):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(X), Y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
        assert losses[-1] < 0.05 * losses[0]

    def test_wrapper_preserves_optimizer_class(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.Adam(model.parameters(), lr=1e-3))
        assert isinstance(opt, torch.optim.Adam)
        assert opt.__class__.__name__ == "Adam"
        assert opt.param_groups[0]["lr"] == 1e-3

    def test_duplicate_named_parameters_rejected(self, thvd):
        model = self._model()
        p = next(model.parameters())
        with pytest.raises(ValueError, match="duplicate"):
            thvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=[("a", p), ("a", p)])

    def test_backward_passes_per_step_accumulates(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        X = torch.randn(8, 4)
        Y = torch.randn(8, 1)
        opt.zero_grad()
        for _ in range(2):
            torch.nn.functional.mse_loss(model(X), Y).backward()
        opt.step()  # must not raise; grads accumulated over 2 passes

    def test_phase_reset_after_warmup_backward(self, thvd):
        # an odd warm-up backward must not permanently shift the
        # backward_passes_per_step accumulation window: synchronize()
        # flushes mid-window grads and resets the counters
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        opt._register_hooks()  # force hooks even at size()==1
        X, Y = torch.randn(8, 4), torch.randn(8, 1)
        torch.nn.functional.mse_loss(model(X), Y).backward()  # warm-up
        opt.synchronize()
        assert not opt._passes and not opt._handles
        opt.zero_grad()
        for _ in range(2):
            torch.nn.functional.mse_loss(model(X), Y).backward()
        # both passes counted in a fresh window: allreduce fired on the 2nd
        assert opt._handles
        opt.step()
        assert not opt._handles and not opt._passes

    def test_model_parallelism_skips_local_params(self, thvd):
        """Params kept out of the optimizer (model-parallel: each worker
        owns them locally) must never be allreduced (reference
        test_torch.py:1119 test_model_parallelism)."""
        model = torch.nn.Sequential(torch.nn.Linear(4, 3),
                                    torch.nn.Linear(3, 1))
        shared = list(model[0].parameters())
        local = list(model[1].parameters())
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(shared, lr=0.01),
            named_parameters=[(f"s{i}", p) for i, p in enumerate(shared)])
        opt._register_hooks()  # force hooks even at size()==1
        torch.nn.functional.mse_loss(
            model(torch.randn(8, 4)), torch.randn(8, 1)).backward()
        assert all(p in opt._passes for p in shared)
        assert all(p not in opt._passes and p not in opt._handles
                   for p in local)
        opt.step()

    def test_dynamic_requires_grad(self, thvd):
        """Freezing a param between steps must not break the hook-driven
        window (reference test_torch.py:1177 dynamic requires_grad)."""
        model = self._model()
        params = list(model.parameters())
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(params, lr=0.01),
            named_parameters=model.named_parameters())
        opt._register_hooks()
        X, Y = torch.randn(8, 4), torch.randn(8, 1)
        torch.nn.functional.mse_loss(model(X), Y).backward()
        opt.step()
        opt.zero_grad()
        frozen = params[0]
        frozen.requires_grad_(False)
        torch.nn.functional.mse_loss(model(X), Y).backward()
        assert frozen not in opt._handles
        opt.step()  # must not raise with the frozen param's stale window

    def test_broadcast_optimizer_state(self, thvd):
        model = self._model()
        base = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        # take a step so momentum buffers exist (the reference's deferred
        # state problem, torch/__init__.py:232-348)
        loss = model(torch.randn(4, 4)).sum()
        loss.backward()
        base.step()
        before = {(pid, k): v.clone() for pid, ps in
                  base.state_dict()["state"].items()
                  for k, v in ps.items() if torch.is_tensor(v)}
        thvd.broadcast_optimizer_state(base, root_rank=0)
        after = {(pid, k): v for pid, ps in
                 base.state_dict()["state"].items()
                 for k, v in ps.items() if torch.is_tensor(v)}
        assert base.param_groups[0]["lr"] == 0.1
        for k in before:
            np.testing.assert_allclose(after[k].numpy(), before[k].numpy())
