"""Native TF AsyncOpKernel collectives (libhvd_tf.so): the compiled-graph
route of the TF frontend — real custom ops over the rank-0-negotiated TCP
ring (_native/src/tf_ops.cc; role of the reference tensorflow/mpi_ops.cc
:276-463 + the MPI CPU ops underneath, common/ops/mpi_operations.cc).

Multi-process cases spawn real workers via run.launch.run, like
test_negotiation.py — the plane's bootstrap (HELLO/ENDPOINTS), negotiation
(READY/ORDER), ring reduce-scatter/allgather, the fp16/bf16 software sum,
and the in-graph fused DistributedOptimizer route all execute for real.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from horovod_tpu.run.launch import run  # noqa: E402

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _native():
    from horovod_tpu.tensorflow import native
    if not native.available():
        pytest.skip("libhvd_tf.so unavailable (no TF headers / toolchain)")
    return native


class TestSingleProcess:
    def test_library_builds_and_loads(self):
        assert _native().available()

    def test_ops_are_identity_at_size_one(self):
        native = _native()
        x = tf.constant([1.0, 2.5, 3.0])
        np.testing.assert_allclose(native.allreduce(x).numpy(), x.numpy())
        np.testing.assert_allclose(native.allgather(x).numpy(), x.numpy())
        np.testing.assert_allclose(native.broadcast(x).numpy(), x.numpy())

    def test_inside_tf_function(self):
        native = _native()

        @tf.function
        def step(t):
            return native.allreduce(t, name="g") * 2.0

        np.testing.assert_allclose(
            step(tf.constant([1.0, 2.0])).numpy(), [2.0, 4.0])

    def test_allgather_scalar_size_one_is_vector(self):
        """At size 1 a scalar input must still come back rank-1: the shape
        fn promises a vector, and the multi-process path delivers one."""
        native = _native()
        out = native.allgather(tf.constant(7.0))
        assert out.shape.rank == 1
        np.testing.assert_allclose(out.numpy(), [7.0])

    def test_allgather_shape_fn_unknown_first_dim(self):
        native = _native()

        @tf.function(input_signature=[
            tf.TensorSpec([4, 3], tf.float32)])
        def g(t):
            out = native.allgather(t, name="ag")
            # graph-time shape: first dim unknown, rest preserved
            assert out.shape.as_list() == [None, 3]
            return out

        assert g(tf.zeros([4, 3])).shape == (4, 3)
