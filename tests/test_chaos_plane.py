"""Chaos-plane drills: deterministic fault injection on the control-plane
transport (run/chaos.py + the hooks in run/network.py), failure detection
(the coordinator's liveness ledger, ops/negotiation.py), and bounded-time
recovery (BasicClient backoff/resend, RanksLostError fail-fast, elastic
auto-shrink).

Every test here is CPU-only, multi-PROCESS at most over the TCP control
plane (never the jax data plane — multiprocess XLA collectives do not
exist on the CPU backend), and bounded by explicit deadlines: the whole
point of the chaos plane is that no failure mode is allowed to hang, so
no drill is allowed to either.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import horovod_tpu
from horovod_tpu.common.config import HorovodConfig
from horovod_tpu.common.exceptions import RanksLostError
from horovod_tpu.ops import negotiation as neg
from horovod_tpu.run import chaos, network
from horovod_tpu.run.elastic import (DrainReplicaRequest,
                                     ElasticSupervisor,
                                     ReplicaSupervisorClient,
                                     ReplicaSupervisorService,
                                     SpawnReplicaRequest)
from horovod_tpu.run.launch import run

KEY = b"k" * 32

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _config(**kw):
    kw.setdefault("fusion_threshold", 0)
    kw.setdefault("stall_warning_time_seconds", 0)
    return HorovodConfig(**kw)


def _addr_map(port):
    return {"local": [("127.0.0.1", port)]}


# module-level so they pickle by reference on the wire
class ApplyRequest:
    def __init__(self, req_id):
        self.req_id = req_id


class ApplyReply:
    def __init__(self, req_id):
        self.req_id = req_id


class CountingService(network.BasicService):
    """Minimal non-dedup'ing service: records every application so tests
    can distinguish applied-once from applied-twice under faults."""

    NAME = "chaos.counting"

    def __init__(self, key):
        self.applied = []
        super().__init__(self.NAME, key)

    def _handle(self, req, client_address):
        if isinstance(req, ApplyRequest):
            self.applied.append(req.req_id)
            return ApplyReply(req.req_id)
        return super()._handle(req, client_address)


@pytest.mark.chaos
class TestChaosSpec:
    def test_malformed_rules_raise(self):
        for bad in ("svc:Msg:drop_request",          # missing prob
                    "svc:Msg:no_such_fault:0.5",     # unknown fault
                    "svc:Msg:drop_request:1.5",      # prob out of range
                    "svc:drop_request:0.5"):         # missing field
            with pytest.raises(ValueError):
                chaos.parse_spec(bad, 0)

    def test_blank_spec_is_empty(self):
        assert chaos.parse_spec("", 0) == []
        assert chaos.parse_spec(" ; ;", 0) == []

    def test_same_seed_same_decisions(self):
        spec = "s:Resp:drop_response:0.3"

        def draws(seed):
            (rule,) = chaos.parse_spec(spec, seed)
            return [rule.fire() for _ in range(200)]

        assert draws(7) == draws(7)
        assert draws(7) != draws(8)

    def test_count_caps_total_injections(self):
        (rule,) = chaos.parse_spec("s:Req:drop_request:1.0:3", 0)
        assert sum(rule.fire() for _ in range(50)) == 3
        assert rule.injected == 3

    def test_injector_filters_by_service(self):
        rules = chaos.parse_spec("hvd.negotiation:*:drop_request:1.0", 0)
        assert not chaos.ChaosInjector("chaos.counting", rules, 50.0)
        inj = chaos.ChaosInjector("hvd.negotiation", rules, 50.0)
        assert inj and inj.decide("request", "CycleRequest") == \
            "drop_request"
        # response-side points never match a request-side fault
        assert inj.decide("response", "CycleResponse") is None

    def test_from_env_without_spec_is_none(self):
        assert "HVD_CHAOS_SPEC" not in os.environ
        assert "HOROVOD_CHAOS_SPEC" not in os.environ
        assert chaos.from_env("hvd.negotiation") is None


@pytest.mark.chaos
class TestClientBackoff:
    def test_full_jitter_bounded_by_cap(self):
        svc = network.BasicService("chaos.backoff", KEY)
        try:
            c = network.BasicClient("chaos.backoff", _addr_map(svc.port),
                                    KEY)
            for attempt in range(12):
                bound = min(0.05 * 2 ** attempt, 1.0)
                for _ in range(8):
                    d = c._backoff_delay(attempt)
                    assert 0.0 <= d <= bound + 1e-9
            # far past the cap crossover: still bounded, no overflow
            assert all(c._backoff_delay(60) <= 1.0 for _ in range(20))
            c.close()
        finally:
            svc.shutdown()


@pytest.mark.chaos
class TestInjectedTransportFaults:
    def test_retry_resends_same_request_verbatim(self, monkeypatch):
        """drop_response with transport retry: the client silently
        reconnects and resends the IDENTICAL request (same req_id on the
        wire) — the property that makes server-side req_id dedup
        sufficient for end-to-end exactly-once."""
        monkeypatch.setenv("HVD_CHAOS_SPEC",
                           "chaos.counting:ApplyReply:drop_response:1.0:1")
        svc = CountingService(KEY)
        try:
            c = network.BasicClient(CountingService.NAME,
                                    _addr_map(svc.port), KEY,
                                    retry_requests=True,
                                    backoff_base_s=0.01)
            resp = c.request(ApplyRequest(7))
            assert isinstance(resp, ApplyReply) and resp.req_id == 7
            # the handler ran twice (apply-then-lose, then the resend);
            # both applications carried the same id
            assert svc.applied == [7, 7]
            assert sum(svc._chaos.stats().values()) == 1
            c.close()
        finally:
            svc.shutdown()

    def test_no_retry_never_double_applies(self, monkeypatch):
        """retry_requests=False: a lost response surfaces as a transport
        error and the request is NOT resent — a non-idempotent service
        sees exactly one application."""
        monkeypatch.setenv("HVD_CHAOS_SPEC",
                           "chaos.counting:ApplyReply:drop_response:1.0:1")
        svc = CountingService(KEY)
        try:
            c = network.BasicClient(CountingService.NAME,
                                    _addr_map(svc.port), KEY)
            with pytest.raises((OSError, EOFError)):
                c.request(ApplyRequest(9))
            assert svc.applied == [9]
            # the rule's count is spent: the next request goes through
            assert c.request(ApplyRequest(10)).req_id == 10
            assert svc.applied == [9, 10]
            c.close()
        finally:
            svc.shutdown()

    def test_truncated_response_reads_as_eof_not_hmac_failure(
            self, monkeypatch):
        monkeypatch.setenv(
            "HVD_CHAOS_SPEC",
            "chaos.counting:ApplyReply:truncate_response:1.0:1")
        svc = CountingService(KEY)
        try:
            c = network.BasicClient(CountingService.NAME,
                                    _addr_map(svc.port), KEY)
            # a mid-frame cut must read as a disconnect (EOFError, which
            # retry logic handles), never as RuntimeError("Security
            # error...") — misdiagnosing faults as auth failures would
            # make every flaky link look like an attack
            with pytest.raises(EOFError):
                c.request(ApplyRequest(1))
            c.close()
        finally:
            svc.shutdown()

    def test_connection_reset_surfaces_as_oserror(self, monkeypatch):
        monkeypatch.setenv("HVD_CHAOS_SPEC",
                           "chaos.counting:ApplyReply:reset:1.0:1")
        svc = CountingService(KEY)
        try:
            c = network.BasicClient(CountingService.NAME,
                                    _addr_map(svc.port), KEY)
            with pytest.raises((OSError, EOFError)):
                c.request(ApplyRequest(1))
            c.close()
        finally:
            svc.shutdown()

    def test_delay_response_is_bounded_by_knob(self, monkeypatch):
        monkeypatch.setenv("HVD_CHAOS_SPEC",
                           "chaos.counting:ApplyReply:delay_response:1.0:1")
        monkeypatch.setenv("HVD_CHAOS_DELAY_MS", "200")
        svc = CountingService(KEY)
        try:
            c = network.BasicClient(CountingService.NAME,
                                    _addr_map(svc.port), KEY)
            t0 = time.monotonic()
            assert c.request(ApplyRequest(3)).req_id == 3
            assert time.monotonic() - t0 >= 0.15
            c.close()
        finally:
            svc.shutdown()

    def test_dup_request_deduped_by_coordinator_req_id(self, monkeypatch):
        """Network-level duplicate delivery of a CycleRequest: the
        handler runs twice, the req_id dedupe collapses it to one
        submission — total ordered work stays exactly one response."""
        monkeypatch.setenv("HVD_CHAOS_SPEC",
                           "hvd.negotiation:CycleRequest:dup_request:1.0:1")
        svc = neg.CoordinatorService(1, KEY, ports=[0], config=_config())
        try:
            c = network.BasicClient(neg.SERVICE_NAME, _addr_map(svc.port),
                                    KEY)
            m = neg.EntryMeta("a", "allreduce", "float32", (4,), 0, False)
            resp = c.request(neg.CycleRequest(0, [m], -1, req_id=1))
            assert sum(svc._chaos.stats().values()) == 1
            assert svc._base_seq + len(svc._responses) == 1
            (r,) = resp.responses
            assert r.kind == r.EXECUTE and r.names == ["a"]
            c.close()
        finally:
            svc.shutdown()


@pytest.mark.chaos
class TestLostResponseInjected:
    def test_dropped_unknown_ids_survive_transport_retry(self, monkeypatch):
        """The ADVICE.md lost-response bug, reproduced with a REAL
        injected fault end-to-end: the first CycleResponse (carrying
        unknown_ids) is dropped on the wire, the client's transport
        retry resends the same req_id, and the deduped retry must return
        the PERSISTED unknown-id verdict. On the pre-fix coordinator the
        retry answered unknown_ids=() and the hit tensors hung forever —
        this test fails on that code."""
        monkeypatch.setenv(
            "HVD_CHAOS_SPEC",
            "hvd.negotiation:CycleResponse:drop_response:1.0:1")
        svc = neg.CoordinatorService(2, KEY, ports=[0], config=_config())
        try:
            c = network.BasicClient(neg.SERVICE_NAME, _addr_map(svc.port),
                                    KEY, retry_requests=True,
                                    backoff_base_s=0.01)
            resp = c.request(neg.CycleRequest(
                0, [], -1, req_id=1, hits=neg.encode_hits([5])))
            assert sum(svc._chaos.stats().values()) == 1  # fault DID fire
            assert resp.unknown_ids == (5,)
            assert svc._seen_req[0] == (1, (5,))
            c.close()
        finally:
            svc.shutdown()


@pytest.mark.chaos
class TestDrillDropResponses:
    def test_negotiation_completes_under_20pct_response_loss(self):
        """Drill (a): 3 real processes negotiate 10 tensors over TCP
        while the coordinator drops 20% of CycleResponses. Required
        outcome: every rank applies the SAME execution order for all 10
        tensors within the deadline — loss slows the control plane, it
        never wedges or reorders it."""
        ports = set()
        while len(ports) < 3:
            ports.add(network.free_port())
        ports_env = ",".join(str(p) for p in sorted(ports))

        def fn():
            import os
            import time

            from horovod_tpu.common.config import HorovodConfig
            from horovod_tpu.ops import negotiation as neg

            rank = int(os.environ.get("HVD_PROCESS_ID", "0"))
            nproc = 3
            addresses = [("127.0.0.1", int(p)) for p in
                         os.environ["HVD_CHAOS_DRILL_PORTS"].split(",")]
            cfg = HorovodConfig(fusion_threshold=0,
                                stall_warning_time_seconds=0)
            worker = neg.NegotiationWorker(rank, nproc, cfg, addresses,
                                           neg.control_key(),
                                           start_timeout_s=60.0)
            names = [f"g{i}" for i in range(10)]
            entries = [neg.EntryMeta(n, "allreduce", "float32", (4,), 0,
                                     False) for n in names]
            applied, ack, req_id = [], -1, 1
            deadline = time.monotonic() + 60.0
            while len(applied) < len(names):
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"rank {rank}: drill deadline exceeded with only "
                        f"{applied} applied")
                try:
                    resp = worker.cycle(entries, ack, req_id=req_id)
                except (OSError, EOFError):
                    # transport retries exhausted: retry the SAME req_id
                    # (the dedupe token) so a half-applied cycle cannot
                    # double-submit
                    time.sleep(0.05)
                    continue
                entries = []  # recorded server-side under this req_id
                req_id += 1
                for i, r in enumerate(resp.responses):
                    seq = resp.base_seq + i
                    if seq <= ack:
                        continue
                    assert seq == ack + 1, "gap in the response log"
                    assert r.kind == r.EXECUTE, r.error
                    applied.extend(r.names)
                    ack = seq
                time.sleep(0.005)
            # final heartbeat delivers ack=9 (the request always lands;
            # only responses are being dropped)
            for _ in range(5):
                try:
                    worker.cycle([], ack, req_id=req_id)
                    break
                except (OSError, EOFError):
                    time.sleep(0.05)
            stats = None
            if rank == 0:
                svc = worker.service
                deadline = time.monotonic() + 60.0
                while not (len(svc._acks) == nproc and
                           min(svc._acks.values()) >= len(names) - 1):
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"acks never converged: {svc._acks}")
                    time.sleep(0.02)
                stats = svc._chaos.stats()
            worker.close(linger_s=0.5)
            return applied, stats

        env = dict(_ENV)
        env["HVD_CHAOS_DRILL_PORTS"] = ports_env
        env["HVD_CHAOS_SPEC"] = \
            "hvd.negotiation:CycleResponse:drop_response:0.2"
        env["HVD_CHAOS_SEED"] = "1234"
        t0 = time.monotonic()
        results = run(fn, num_proc=3, env=env, start_timeout_s=180.0)
        elapsed = time.monotonic() - t0
        assert elapsed < 120.0, f"drill took {elapsed:.1f}s"
        orders = [applied for applied, _ in results]
        assert sorted(orders[0]) == [f"g{i}" for i in range(10)]
        assert orders[1] == orders[0] and orders[2] == orders[0]
        stats = results[0][1]
        assert stats is not None and sum(stats.values()) > 0, \
            f"the drill injected nothing: {stats}"


_VICTIM_SCRIPT = r"""
import sys, time
from horovod_tpu.common.config import HorovodConfig
from horovod_tpu.ops import negotiation as neg

port = int(sys.argv[1])
cfg = HorovodConfig(fusion_threshold=0, stall_warning_time_seconds=0)
w = neg.NegotiationWorker(1, 3, cfg, [("127.0.0.1", port)], b"k" * 32,
                          start_timeout_s=30.0)
req_id = 1
while True:  # heartbeat forever, until SIGKILLed by the test
    try:
        w.cycle([], -1, req_id=req_id)
        req_id += 1
    except Exception:
        pass
    time.sleep(0.1)
"""


@pytest.mark.chaos
class TestDrillWorkerKilled:
    def test_killed_rank_fails_fast_with_ranks_lost(self):
        """Drill (b): SIGKILL one worker mid-negotiation. Survivors must
        receive RanksLostError NAMING the dead rank within a bounded
        interval — never the legacy stall-warning hang — and the
        coordinator must fail the pending work it can no longer
        complete."""
        cfg = _config(rank_lost_timeout_seconds=1.5)
        svc = neg.CoordinatorService(3, KEY, ports=[0], config=cfg)
        victim = worker2 = None
        try:
            venv = dict(os.environ)
            venv["JAX_PLATFORMS"] = "cpu"
            venv["PALLAS_AXON_POOL_IPS"] = ""
            venv["PYTHONPATH"] = os.pathsep.join(
                [os.path.dirname(os.path.dirname(horovod_tpu.__file__))] +
                venv.get("PYTHONPATH", "").split(os.pathsep))
            victim = subprocess.Popen(
                [sys.executable, "-c", _VICTIM_SCRIPT, str(svc.port)],
                env=venv, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            # rank 1 is "up" once its first heartbeat lands in the ledger
            deadline = time.monotonic() + 60.0
            while 1 not in svc._last_seen:
                assert time.monotonic() < deadline, \
                    "victim never heartbeated"
                assert victim.poll() is None, \
                    f"victim died early (rc={victim.poll()})"
                time.sleep(0.05)
            worker2 = neg.NegotiationWorker(2, 3, cfg,
                                            [("127.0.0.1", svc.port)],
                                            KEY, start_timeout_s=30.0)
            m = neg.EntryMeta("w", "allreduce", "float32", (4,), 0, False)
            # ranks 0 and 2 announce "w"; rank 1 never will
            svc._handle(neg.CycleRequest(0, [m], -1, req_id=1), ("", 0))
            resp = worker2.cycle([m], -1, req_id=1)
            assert resp.lost_ranks == ()
            victim.send_signal(signal.SIGKILL)
            victim.wait(timeout=10.0)
            t0 = time.monotonic()
            err = None
            req_id = 2
            while time.monotonic() - t0 < 15.0:
                # both survivors keep cycling (their heartbeats also
                # drive the coordinator's liveness scan)
                svc._handle(neg.CycleRequest(0, [], -1, req_id=req_id),
                            ("", 0))
                try:
                    neg.raise_if_ranks_lost(
                        worker2.cycle([], -1, req_id=req_id))
                except RanksLostError as e:
                    err = e
                    break
                req_id += 1
                time.sleep(0.1)
            elapsed = time.monotonic() - t0
            assert err is not None, \
                "survivors never saw RanksLostError (the legacy hang)"
            assert elapsed < 10.0, f"fail-fast took {elapsed:.1f}s"
            assert err.ranks == (1,)
            assert "1" in str(err)
            # the pending tensor was failed, not stranded
            errors = [r for r in svc._responses if r.kind == r.ERROR]
            assert any("RanksLostError" in r.error and r.names == ["w"]
                       for r in errors), errors
        finally:
            if victim is not None and victim.poll() is None:
                victim.kill()
                victim.wait(timeout=10.0)
            if worker2 is not None:
                worker2.close(linger_s=0.0)
            svc.shutdown()


@pytest.mark.chaos
class TestDrillPostmortem:
    def test_flight_dumps_and_postmortem_name_the_faulted_rank(
            self, tmp_path):
        """Drill (c), the tracing plane end to end: 3 real processes,
        every CycleResponse dropped on the wire. Each rank's coordinator
        escalates past the poison grace (RanksLostError naming rank 0),
        auto-dumping its flight recorder to the shared HVD_FLIGHT_DIR —
        then THIS process runs hvd_postmortem over the dumps and the
        verdict must name the faulted rank, the blocking tensor and the
        chaos injections as probable cause. No hand-built fixtures: the
        dumps are exactly what a real incident leaves behind."""

        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common.exceptions import RanksLostError
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            # enqueue immediately: the negotiate span must be open (and
            # announced) well before the ~2s escalation fires
            h = hvd.allreduce_async(np.ones((8,), np.float32),
                                    average=False, name="grad_drill")
            err = None
            try:
                hvd.synchronize(h)
            except RanksLostError as e:
                err = str(e)
            finally:
                try:
                    hvd.shutdown()
                except Exception:  # hvdlint: disable=HVD006(teardown of an already-failed job is best-effort)
                    pass
            return (r, err)

        env = dict(_ENV)
        env["HVD_FLIGHT_DIR"] = str(tmp_path)
        env["HVD_CHAOS_SPEC"] = \
            "hvd.negotiation:CycleResponse:drop_response:1.0"
        env["HVD_CHAOS_SEED"] = "7"
        env["HVD_COORDINATOR_LOST_TIMEOUT_SECONDS"] = "2.0"
        results = run(fn, num_proc=3, env=env, start_timeout_s=180.0)

        by_rank = dict(results)
        assert sorted(by_rank) == [0, 1, 2]
        for r, err in by_rank.items():
            assert err is not None, \
                f"rank {r} never saw RanksLostError under 100% loss"
            assert "0" in err  # the error names the lost rank
        # at least one rank had pending work whose trace id made it
        # into the error text end-to-end
        assert any("[trace " in err for err in by_rank.values()), by_rank

        dumps = sorted(p.name for p in tmp_path.glob("flight-rank*.json"))
        assert dumps == [f"flight-rank{r}.json" for r in range(3)], dumps

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_postmortem
        paths = hvd_postmortem.find_dumps(str(tmp_path))
        loaded, bad = hvd_postmortem.load_dumps(paths)
        assert not bad and len(loaded) == 3
        hvd_postmortem.rebase(loaded)
        verdict = hvd_postmortem.analyze(loaded)
        assert verdict["divergent_rank"] == 0, verdict
        assert verdict["tensor"] == "grad_drill", verdict
        assert verdict["trace_id"], verdict
        assert verdict["chaos_injections"], \
            "rank 0's rings carry no chaos breadcrumbs"
        assert "grad_drill" in verdict["waiting"]
        # and the CLI renders the same story without crashing
        report = hvd_postmortem.render_report(
            loaded, [], verdict, hvd_postmortem.last_cycles(loaded, 8), 0)
        assert "divergent rank : 0" in report
        assert "grad_drill" in report


@pytest.mark.chaos
class TestDrillNumericsDivergence:
    def test_poisoned_rank_yields_postmortem_verdict(self, tmp_path):
        """Drill (d), the numerics plane end to end: 3 real processes
        drive the negotiated control plane over TCP while each rank's
        REAL NumericsMonitor digests its own gradient stream. Rank 0's
        gradients are NaN-poisoned from cycle 2 on; the coordinator's
        divergence sentinel must name rank 0, the tensor, and the first
        bad cycle, solicit flight dumps from every rank — and
        hvd_postmortem over the resulting dumps must reach the same
        verdict. (The data plane never runs: multiprocess XLA
        collectives do not exist on the CPU backend — the digests are
        the product of the same observe path the eager flush feeds.)"""

        port = network.free_port()

        def fn():
            import os
            import time
            import numpy as np
            from horovod_tpu.common.config import HorovodConfig
            from horovod_tpu.ops import negotiation as neg
            from horovod_tpu.utils import metrics as hvd_metrics
            from horovod_tpu.utils import numerics as hvd_numerics
            from horovod_tpu.utils import tracing as hvd_tracing

            rank = int(os.environ["HVD_PROCESS_ID"])
            nproc = 3
            addresses = [("127.0.0.1",
                          int(os.environ["HVD_CHAOS_DRILL_PORTS"]))]
            hvd_metrics.get_registry().rank = rank
            hvd_tracing.reset(enabled=True, rank=rank)
            mon = hvd_numerics.reset(enabled=True)
            cfg = HorovodConfig(fusion_threshold=0,
                                stall_warning_time_seconds=0)
            worker = neg.NegotiationWorker(rank, nproc, cfg, addresses,
                                           neg.control_key(),
                                           start_timeout_s=60.0)
            healthy_red = np.full((16,), 3.0, np.float32)
            solicited = False
            req_id = 0
            try:
                for cyc in range(5):
                    loc = np.full((16,), 1.0 + rank, np.float32)
                    red = healthy_red
                    if rank == 0 and cyc >= 2:
                        loc = loc.copy()
                        loc[::4] = np.nan  # the injected perturbation
                        # a poisoned replica reduces its own corrupt
                        # copy; the healthy peers' post-state disagrees
                        red = loc
                    recs = mon.observe([("grad_poison", loc, red)],
                                       cycle=cyc)
                    digest = hvd_numerics.fold_digest(None, cyc, recs,
                                                      rank=rank)
                    req_id += 1
                    resp = worker.cycle([], -1, req_id=req_id,
                                        digest=digest)
                    solicited = solicited or resp.dump_requested
                # keep heartbeating until the coordinator's escalation
                # solicits a flight dump (it races the loop above)
                deadline = time.monotonic() + 30.0
                while not solicited:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"rank {rank}: dump never solicited")
                    req_id += 1
                    solicited = worker.cycle(
                        [], -1, req_id=req_id).dump_requested
                    time.sleep(0.02)
                # attach this rank's flight snapshot for the coordinator
                # to persist (eager's loop does this automatically; the
                # drill drives the protocol by hand)
                req_id += 1
                worker.cycle([], -1, req_id=req_id,
                             flight=hvd_tracing.get_tracer()
                             .flight_snapshot("solicited"))
                flagged = first_bad = None
                if rank == 0:
                    svc = worker.service
                    deadline = time.monotonic() + 30.0
                    while len(svc.flight_dumps) < nproc:
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                f"dumps missing: "
                                f"{sorted(svc.flight_dumps)}")
                        time.sleep(0.02)
                    flagged = dict(svc._numerics_flagged)
                    first_bad = dict(svc._numerics_first_bad)
                return rank, flagged, first_bad
            finally:
                worker.close(linger_s=1.0)

        env = dict(_ENV)
        env["HVD_FLIGHT_DIR"] = str(tmp_path)
        env["HVD_CHAOS_DRILL_PORTS"] = str(port)
        results = run(fn, num_proc=3, env=env, start_timeout_s=180.0)

        by_rank = {r: (flagged, first_bad)
                   for r, flagged, first_bad in results}
        assert sorted(by_rank) == [0, 1, 2]
        flagged, first_bad = by_rank[0]
        # the live sentinel named the rank, the tensor, the first cycle
        assert flagged.get((2, "grad_poison", "nonfinite")) == 0, flagged
        assert any(kind == "divergence" and blamed == 0
                   for (_, _, kind), blamed in flagged.items()), flagged
        assert first_bad == {"grad_poison": 2}

        dumps = sorted(p.name for p in tmp_path.glob("flight-rank*.json"))
        assert dumps == [f"flight-rank{r}.json" for r in range(3)], dumps

        # ...and the offline postmortem reaches the same verdict from
        # nothing but the dumps
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_postmortem
        paths = hvd_postmortem.find_dumps(str(tmp_path))
        loaded, bad = hvd_postmortem.load_dumps(paths)
        assert not bad and len(loaded) == 3
        hvd_postmortem.rebase(loaded)
        verdict = hvd_postmortem.analyze(loaded)
        assert verdict["divergent_rank"] == 0, verdict
        assert verdict["tensor"] == "grad_poison", verdict
        assert verdict["first_bad_cycle"] == 2, verdict
        assert verdict["numerics_anomalies"], verdict
        assert any("numerics" in r for r in verdict["reasons"]), verdict
        report = hvd_postmortem.render_report(
            loaded, [], verdict, hvd_postmortem.last_cycles(loaded, 8), 0)
        assert "divergent rank : 0" in report
        assert "first bad cycle: 2" in report
        assert "grad_poison" in report


class _ExitedProc:
    """A job process that has already exited with a scripted code."""

    def __init__(self, rc):
        self._rc = rc
        self.pid = os.getpid()

    def wait(self, timeout=None):
        return self._rc

    def poll(self):
        return self._rc


@pytest.mark.chaos
class TestElasticAutoShrink:
    def _supervisor(self, rcs, calls, hosts="localhost:4", **kw):
        codes = list(rcs)

        def runner(argv):
            calls.append(list(argv))
            return _ExitedProc(codes.pop(0))

        kw.setdefault("auto_shrink_rc", RanksLostError.EXIT_CODE)
        return ElasticSupervisor(hosts, ["job", "{np}", "{bpa}",
                                         "{restart}"],
                                 ports=(0,), verbose=0, runner=runner, **kw)

    def test_ranks_lost_exit_shrinks_and_restarts(self):
        calls = []
        sup = self._supervisor([RanksLostError.EXIT_CODE, 0], calls)
        try:
            sup.start()
            assert sup.wait(poll_s=0.01) == 0
        finally:
            sup.shutdown()
        assert sup.restarts == 1
        # 4 slots, shrink 1 -> 3, then to 2 so 4 % total == 0 (exact
        # global-batch preservation via batches-per-allreduce)
        assert sup.current_total == 2
        assert calls == [["job", "4", "1", "0"], ["job", "2", "2", "1"]]

    def test_other_exit_codes_pass_through(self):
        calls = []
        sup = self._supervisor([3], calls)
        try:
            sup.start()
            assert sup.wait(poll_s=0.01) == 3
        finally:
            sup.shutdown()
        assert sup.restarts == 0 and len(calls) == 1

    def test_max_restarts_bounds_the_loop(self):
        calls = []
        rc = RanksLostError.EXIT_CODE
        sup = self._supervisor([rc, rc, rc], calls, max_restarts=2)
        try:
            sup.start()
            # shrinks twice (4 -> 2 -> 1), then surfaces the code
            assert sup.wait(poll_s=0.01) == rc
        finally:
            sup.shutdown()
        assert sup.restarts == 2 and len(calls) == 3

    def test_unshrinkable_allocation_surfaces_the_code(self):
        calls = []
        sup = self._supervisor([RanksLostError.EXIT_CODE], calls,
                               hosts="localhost:1")
        try:
            sup.start()
            # 1 slot cannot shrink: the failure surfaces instead of
            # looping
            assert sup.wait(poll_s=0.01) == RanksLostError.EXIT_CODE
        finally:
            sup.shutdown()
        assert sup.restarts == 0


@pytest.mark.chaos
class TestDrillServingReplicaLost:
    def test_replica_loss_is_bounded_and_postmortem_names_the_rank(
            self, tmp_path):
        """Drill (f), the serving plane: 2 replica processes on the
        negotiation control plane. Replica 1 wedges mid-stream (stops
        heartbeating but stays alive — the nasty case: no TCP reset, no
        exit code). Replica 0's engine must turn that silence into a
        bounded-time failover — RanksLostError via its per-step
        heartbeat, a serve_failover event, a flight dump — and KEEP
        SERVING: requests submitted after the failover still complete.
        Then THIS process runs hvd_postmortem over the dumps and the
        verdict must name the lost replica.

        The whole drill runs under HVD_LOCKDEP=1: every control-plane
        lock (coordinator, admission queue, tracer, metrics) is the
        instrumented kind, and the healthy path must produce ZERO
        lockdep findings — no inversions, no stalls — even while a
        peer wedges and the engine fails over."""

        def fn():
            import os
            import time
            import jax
            import jax.numpy as jnp
            from horovod_tpu.models import transformer as tr
            from horovod_tpu.serving.engine import ServeEngine
            from horovod_tpu.serving.queue import AdmissionQueue, Request
            from horovod_tpu.serving.replica import ReplicaGroup
            from horovod_tpu.utils import lockdep
            from horovod_tpu.utils import tracing as hvd_tracing

            r = int(os.environ["HVD_PROCESS_ID"])
            port = int(os.environ["DRILL_PORT"])
            done_file = os.environ["DRILL_DONE_FILE"]
            hvd_tracing.reset(enabled=True, rank=r)
            if r == 1:
                group = ReplicaGroup(r, 2, ("127.0.0.1", port),
                                     key=b"k" * 32,
                                     rank_lost_timeout_s=1.5,
                                     start_timeout_s=120.0)
                # the victim: a few healthy heartbeats, then silence
                for _ in range(3):
                    group.heartbeat()
                    time.sleep(0.05)
                deadline = time.monotonic() + 120.0
                while not os.path.exists(done_file) and \
                        time.monotonic() < deadline:
                    time.sleep(0.1)
                group.close(linger_s=0.0)
                return (r, None, None, None, lockdep.findings())

            # replica 0: a real serving engine riding the group. Warm
            # the jit caches BEFORE joining — multi-second compiles
            # inside the group would stall rank 0's own heartbeats past
            # the 1.5s window and the coordinator's ledger (triggered by
            # the victim's cycles) would declare the WRONG rank lost.
            cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                            attention_impl="full")
            _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
            warm = ServeEngine(
                cfg, params, num_slots=2, max_len=32, kv_block=8,
                queue=AdmissionQueue(max_depth=8,
                                     admission_timeout_s=1e9))
            warm.submit(Request("warm", (3, 1, 4), max_new_tokens=4))
            warm.run_to_completion()
            group = ReplicaGroup(r, 2, ("127.0.0.1", port), key=b"k" * 32,
                                 rank_lost_timeout_s=1.5,
                                 start_timeout_s=120.0)
            lost_box = []
            queue = AdmissionQueue(max_depth=32, admission_timeout_s=1e9)
            engine = ServeEngine(
                cfg, params, num_slots=2, max_len=32, kv_block=8,
                queue=queue, replica=group,
                on_ranks_lost=lost_box.append)
            for i in range(2):
                engine.submit(Request(f"pre-{i}", (3, 1, 4),
                                      max_new_tokens=24))
            results = []
            t0 = time.monotonic()
            detect_s = None
            while time.monotonic() - t0 < 60.0:
                results.extend(engine.step())
                if lost_box:
                    detect_s = time.monotonic() - t0
                    break
                # pace the decode so pre-* are still mid-stream when
                # the loss lands: the flight dump must catch real
                # in-flight work, not an idle engine
                time.sleep(0.15)
            # release the victim before any assertion can exit early
            with open(done_file, "w") as f:
                f.write("done")
            # failover must not stop the music: post-loss requests serve
            for i in range(2):
                engine.submit(Request(f"post-{i}", (1, 2),
                                      max_new_tokens=4))
            results.extend(engine.run_to_completion())
            completed = sorted(x.request_id for x in results
                               if x.outcome == "completed")
            return (r, detect_s, lost_box, completed, lockdep.findings())

        env = dict(_ENV)
        env["HVD_FLIGHT_DIR"] = str(tmp_path)
        env["HVD_LOCKDEP"] = "1"
        env["DRILL_PORT"] = str(network.free_port())
        env["DRILL_DONE_FILE"] = str(tmp_path / "victim.done")
        results = run(fn, num_proc=2, env=env, start_timeout_s=180.0)

        by_rank = {x[0]: x for x in results}
        _, detect_s, lost_box, completed, _ = by_rank[0]
        # the lock-order sanitizer rode the whole drill on both
        # replicas: the healthy path must be finding-free
        for rank, result in sorted(by_rank.items()):
            assert result[4] == [], (
                f"lockdep findings on replica {rank}: {result[4]}")
        assert detect_s is not None, \
            "replica 0 never detected the wedged peer (the silent hang)"
        assert detect_s < 30.0, f"detection took {detect_s:.1f}s"
        assert lost_box == [(1,)], lost_box
        # serving continued through the failover: every request —
        # submitted before AND after the loss — completed
        assert completed == ["post-0", "post-1", "pre-0", "pre-1"]

        # the drill leaves real dumps behind; the postmortem must blame
        # the lost replica from them alone
        dumps = sorted(p.name for p in tmp_path.glob("flight-rank*.json"))
        assert "flight-rank0.json" in dumps, dumps
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_postmortem
        loaded, bad = hvd_postmortem.load_dumps(
            hvd_postmortem.find_dumps(str(tmp_path)))
        assert not bad
        hvd_postmortem.rebase(loaded)
        verdict = hvd_postmortem.analyze(loaded)
        assert verdict["divergent_rank"] == 1, verdict

        # the dump caught the in-flight requests: their request-path
        # spans are open, the failover event names them, and both
        # analyzers surface them by id
        (dump0,) = [d for d in loaded if d.get("rank") == 0]
        open_requests = sorted(
            s["tensor"] for s in dump0.get("open_spans", [])
            if s.get("stage") == "request")
        assert open_requests == ["pre-0", "pre-1"], dump0.get(
            "open_spans")
        (failover,) = [e for e in dump0.get("events", [])
                       if e.get("event") == "serve_failover"]
        assert failover["inflight"] == ["pre-0", "pre-1"], failover
        assert verdict["inflight_requests"] == ["pre-0", "pre-1"], \
            verdict
        assert any("pre-0" in r for r in verdict["reasons"]), \
            verdict["reasons"]
        import hvd_slo
        slo = hvd_slo.analyze_serve(loaded)
        assert slo["inflight"] == ["pre-0", "pre-1"], slo
        assert "pre-0" in slo["verdict"], slo["verdict"]


# ---------------------------------------------------------------------------
# checkpoint-plane drills: a real trainer subprocess under a real
# ElasticSupervisor, killed for real. Deterministic numpy "training"
# (per-step seeded data, loss depends on the whole weight history) so a
# wrong resume shows up as a diverged loss trajectory, not a vibe.
# ---------------------------------------------------------------------------

_DRILL_TRAINER = """\
import os, sys, time

import numpy as np

from horovod_tpu import trainer
from horovod_tpu.common.exceptions import PREEMPTED_EXIT_CODE

ck = trainer.Checkpointer(os.environ["DRILL_CKPT"],
                          every=int(os.environ["DRILL_EVERY"]),
                          async_save=False)
state, start, extra = ck.resume(like={"w": np.zeros(4)})
w = np.asarray(state["w"], dtype=np.float64)
steps = int(os.environ["DRILL_STEPS"])
f = open(os.environ["DRILL_PROG"], "a")
for i in range(start, steps):
    rng = np.random.default_rng(i)  # data position == step: resumable
    g = rng.standard_normal(4)
    w = w - 0.5 * (w - g)
    loss = float(np.sum((w - g) ** 2))
    f.write(f"{i + 1} {loss!r}\\n")
    f.flush()
    os.fsync(f.fileno())
    time.sleep(float(os.environ["DRILL_SLEEP"]))
    if ck.step_end(i + 1, {"w": w}, extra={"data_pos": i + 1}):
        sys.exit(PREEMPTED_EXIT_CODE)
ck.close()
"""


def _drill_trajectory(steps):
    """The uninterrupted run's exact (step, loss) sequence, computed
    in-process with the same arithmetic the drill trainer executes."""
    w = np.zeros(4, dtype=np.float64)
    out = []
    for i in range(steps):
        rng = np.random.default_rng(i)
        g = rng.standard_normal(4)
        w = w - 0.5 * (w - g)
        out.append((i + 1, float(np.sum((w - g) ** 2))))
    return out


def _progress_lines(path):
    if not os.path.exists(path):
        return []
    out = []
    for line in open(path).read().splitlines():
        parts = line.split()
        if len(parts) == 2:  # a kill can tear the final line mid-write
            try:
                out.append((int(parts[0]), float(parts[1])))
            except ValueError:
                pass
    return out


class _CapturingRunner:
    """ElasticSupervisor runner that launches the real subprocess and
    remembers it so the drill can deliver signals to the CURRENT job."""

    def __init__(self, env):
        self.env = env
        self.procs = []

    def __call__(self, argv):
        p = subprocess.Popen(argv, env=self.env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        self.procs.append(p)
        return p


def _run_drill(tmp_path, steps, every, sig, sup_kwargs,
               min_lines_before_kill, rto_bound_s=90.0):
    """Start the drill trainer under a supervisor, kill it once it has
    made progress, and return (exit_code, supervisor, runner, rto_s)."""
    import threading

    prog = str(tmp_path / "progress.log")
    env = dict(os.environ, **_ENV)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))] +
        env.get("PYTHONPATH", "").split(os.pathsep))
    env.update(DRILL_CKPT=str(tmp_path / "ckpt"), DRILL_PROG=prog,
               DRILL_STEPS=str(steps), DRILL_EVERY=str(every),
               DRILL_SLEEP="0.15")
    script = tmp_path / "drill_trainer.py"
    script.write_text(_DRILL_TRAINER)
    runner = _CapturingRunner(env)
    sup = ElasticSupervisor("localhost:2",
                            [sys.executable, str(script)],
                            ports=(0,), verbose=0, runner=runner,
                            **sup_kwargs)
    box = []
    sup.start()
    waiter = threading.Thread(target=lambda: box.append(
        sup.wait(poll_s=0.1)), daemon=True)
    waiter.start()
    try:
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and \
                len(_progress_lines(prog)) < min_lines_before_kill:
            time.sleep(0.05)
        lines_at_kill = _progress_lines(prog)
        assert len(lines_at_kill) >= min_lines_before_kill, \
            "drill trainer made no progress before the kill"
        os.kill(runner.procs[-1].pid, sig)
        t_kill = time.monotonic()
        # RTO: wall clock from kill to the restarted job's first NEW step
        rto = None
        deadline = t_kill + rto_bound_s
        while time.monotonic() < deadline:
            lines = _progress_lines(prog)
            if lines and lines[-1][0] > lines_at_kill[-1][0]:
                rto = time.monotonic() - t_kill
                break
            time.sleep(0.05)
        assert rto is not None, (
            f"no recovery within the {rto_bound_s:.0f}s RTO bound after "
            f"{signal.Signals(sig).name}")
        waiter.join(timeout=120.0)
        assert box, "supervised job never reached a terminal exit"
        return box[0], sup, runner, rto
    finally:
        sup.shutdown()


@pytest.mark.chaos
class TestDrillCheckpointRestart:
    def test_sigkill_bounded_rto_and_exact_loss_trajectory(self,
                                                           tmp_path):
        """Drill (g), the checkpoint plane's reason to exist: SIGKILL a
        training process mid-run — no handler, no goodbye — under a
        supervisor consuming crashes. Recovery must be bounded in time,
        and the completed run's loss trajectory must match the
        uninterrupted run EXACTLY: same steps, same floats. Anything
        else means resume restored the wrong weights, step, or data
        position."""
        rc, sup, runner, rto = _run_drill(
            tmp_path, steps=10, every=1, sig=signal.SIGKILL,
            sup_kwargs=dict(auto_shrink_rc=-signal.SIGKILL),
            min_lines_before_kill=3)
        assert rc == 0
        assert sup.restarts == 1 and len(runner.procs) == 2
        assert rto < 90.0, f"RTO {rto:.1f}s"
        lines = _progress_lines(str(tmp_path / "progress.log"))
        # a SIGKILL between the progress write and the step_end() commit
        # legally re-runs that one step after resume; the LAST occurrence
        # of every step is the run's verdict
        final = dict(lines)
        expect = dict(_drill_trajectory(10))
        assert sorted(final) == sorted(expect), \
            f"missing/extra steps: got {sorted(final)}"
        for s in expect:
            assert abs(final[s] - expect[s]) < 1e-12, (
                f"loss diverged at step {s}: {final[s]!r} != "
                f"{expect[s]!r} — resume restored the wrong state")
        # each step ran at most twice (the in-flight one), never more
        seen = [s for s, _ in lines]
        assert all(seen.count(s) <= 2 for s in set(seen))

    def test_sigterm_preemption_exits_45_and_no_step_reruns(self,
                                                            tmp_path):
        """Drill (h), preemption-safe exit: SIGTERM must let the
        in-flight step finish, commit an EMERGENCY checkpoint (the
        periodic cadence is every=3 — without the emergency save, steps
        would re-run), exit PREEMPTED_EXIT_CODE, and restart with the
        SAME slots via graceful_restart_rc. The emergency save makes
        resume exact: every step appears EXACTLY once."""
        from horovod_tpu.common.exceptions import PREEMPTED_EXIT_CODE
        rc, sup, runner, rto = _run_drill(
            tmp_path, steps=9, every=3, sig=signal.SIGTERM,
            sup_kwargs=dict(graceful_restart_rc=PREEMPTED_EXIT_CODE),
            min_lines_before_kill=4)
        assert rc == 0
        assert sup.restarts == 1 and len(runner.procs) == 2
        assert runner.procs[0].wait() == PREEMPTED_EXIT_CODE
        assert sup.current_total == 2  # graceful restart never shrinks
        lines = _progress_lines(str(tmp_path / "progress.log"))
        seen = [s for s, _ in lines]
        assert seen == list(range(1, 10)), (
            f"steps must each run exactly once (emergency checkpoint "
            f"resumes at the exact boundary): {seen}")
        expect = dict(_drill_trajectory(9))
        for s, loss in lines:
            assert abs(loss - expect[s]) < 1e-12


# ---------------------------------------------------------------------------
# fleet drill: the whole train->serve weight path under fire. A real
# publishing trainer (subprocess, preempted mid-run) feeds a serving
# replica pair over the negotiation control plane; the replica hot-swaps
# generations mid-traffic, loses its peer, and every injected event must
# be named by the postmortem from the flight dumps alone.
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestDrillFleetHotSwap:
    def test_preemption_replica_loss_swaps_and_parity(self, tmp_path):
        """Drill (i), the fleet plane end to end: a publishing trainer
        runs as a subprocess under an ElasticSupervisor and is SIGTERMed
        mid-traffic (exit 45, emergency publish-commit, same-slot
        restart — the TPU preemption shape). Two replica processes serve
        open-loop Poisson traffic on the control plane; replica 0's
        engine must hot-swap through >=2 published generations WHILE
        decoding (zero drain), survive replica 1 wedging mid-stream, and
        complete every request. Temp-0 parity: each request's tokens
        must be bit-exact against a fresh engine running that
        generation's recomputed weights — a swap that armed the wrong
        bytes diverges here, not in a dashboard. Then hvd_postmortem
        must name every injected event from the dumps: the lost replica,
        the preemption's emergency commit, and each weight swap."""
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_fleet
        import hvd_postmortem

        ckpt_dir = str(tmp_path / "ckpt")
        traffic_started = str(tmp_path / "traffic.started")
        wedge_now = str(tmp_path / "wedge.now")
        done_file = str(tmp_path / "victim.done")

        # pre-publish generation 1 (the trainer's exact step-0 state) so
        # the replica can boot before the trainer exists; the trainer's
        # publisher resumes the generation counter from the pointer
        import jax
        import jax.numpy as jnp
        from horovod_tpu.fleet import WeightPublisher
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.utils import checkpoint as hvd_checkpoint
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                        attention_impl="full")
        _, params0 = tr.init_params(cfg, jax.random.PRNGKey(0))
        mgr = hvd_checkpoint.CheckpointManager(ckpt_dir, rank=0,
                                               world_size=1,
                                               async_save=False)
        mgr.on_commit = WeightPublisher(ckpt_dir).publish
        mgr.save(params0, step=0, block=True)
        mgr.close()

        trainer_env = dict(os.environ, **_ENV)
        trainer_env["HVD_FLIGHT_DIR"] = str(tmp_path)

        def fn():
            import os
            import time
            import jax
            import jax.numpy as jnp
            from horovod_tpu.fleet import WeightSubscriber
            from horovod_tpu.models import transformer as tr
            from horovod_tpu.serving.engine import ServeEngine
            from horovod_tpu.serving.queue import AdmissionQueue, Request
            from horovod_tpu.serving.replica import ReplicaGroup
            from horovod_tpu.utils import checkpoint as hvd_checkpoint
            from horovod_tpu.utils import tracing as hvd_tracing

            r = int(os.environ["HVD_PROCESS_ID"])
            port = int(os.environ["DRILL_PORT"])
            ckpt = os.environ["DRILL_CKPT"]
            hvd_tracing.reset(enabled=True, rank=r)
            if r == 1:
                group = ReplicaGroup(r, 2, ("127.0.0.1", port),
                                     key=b"k" * 32,
                                     rank_lost_timeout_s=2.0,
                                     start_timeout_s=120.0)
                # healthy heartbeats until told to wedge, then silence
                deadline = time.monotonic() + 180.0
                while not os.path.exists(
                        os.environ["DRILL_WEDGE_FILE"]) and \
                        time.monotonic() < deadline:
                    group.heartbeat()
                    time.sleep(0.1)
                deadline = time.monotonic() + 180.0
                while not os.path.exists(os.environ["DRILL_DONE_FILE"]) \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                group.close(linger_s=0.0)
                return (r, None, None, None, None, None, None)

            # replica 0: warm the jit caches BEFORE joining the group
            # (compiles inside would stall heartbeats past the window)
            cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                            attention_impl="full")
            _, params0 = tr.init_params(cfg, jax.random.PRNGKey(0))
            warm = ServeEngine(
                cfg, params0, num_slots=2, max_len=48, kv_block=8,
                queue=AdmissionQueue(max_depth=8,
                                     admission_timeout_s=1e9))
            warm.submit(Request("warm", (3, 1, 4), max_new_tokens=4))
            warm.run_to_completion()

            # subscribe to the trainer's publications (boot generation)
            deadline = time.monotonic() + 120.0
            while hvd_checkpoint.latest_manifest(ckpt) is None:
                if time.monotonic() > deadline:
                    raise RuntimeError("trainer never published")
                time.sleep(0.05)
            sub = WeightSubscriber(ckpt, like=params0,
                                   poll_interval_s=0.25)
            boot = sub.load_initial()
            gen_step = {boot.generation: boot.step}

            group = ReplicaGroup(r, 2, ("127.0.0.1", port),
                                 key=b"k" * 32, rank_lost_timeout_s=2.0,
                                 start_timeout_s=120.0)
            lost_box = []
            queue = AdmissionQueue(max_depth=64,
                                   admission_timeout_s=1e9)
            engine = ServeEngine(cfg, boot.params, num_slots=2,
                                 max_len=48, kv_block=8, queue=queue,
                                 replica=group, subscriber=sub,
                                 on_ranks_lost=lost_box.append)

            import hvd_fleet as hf
            workload = hf.make_workload(
                0, 12, 0.5,
                lambda rid, prompt, n: Request(rid, prompt,
                                               max_new_tokens=n))
            results = []
            i = steps = 0
            wedged = False
            deadline = time.monotonic() + 180.0
            while (i < len(workload) or engine.active_count or
                   len(engine.queue)) and time.monotonic() < deadline:
                while i < len(workload) and workload[i][0] <= steps:
                    engine.submit(workload[i][1])
                    i += 1
                results.extend(engine.step())
                steps += 1
                swap = engine.last_swap
                if swap and swap["generation"] not in gen_step:
                    gen_step[swap["generation"]] = swap["step"]
                if results and not os.path.exists(
                        os.environ["DRILL_START_FILE"]):
                    with open(os.environ["DRILL_START_FILE"], "w") as f:
                        f.write("started")  # main SIGTERMs the trainer
                if not wedged and len(gen_step) >= 2 and \
                        len(results) >= 3:
                    with open(os.environ["DRILL_WEDGE_FILE"], "w") as f:
                        f.write("wedge")  # inject the replica loss
                    wedged = True
                time.sleep(0.1)
            # keep polling until >=2 swaps landed and the loss was seen
            # (the wedge may still be pending if traffic drained fast)
            deadline = time.monotonic() + 90.0
            while (len(gen_step) < 3 or not wedged or not lost_box) and \
                    time.monotonic() < deadline:
                engine.step()
                swap = engine.last_swap
                if swap and swap["generation"] not in gen_step:
                    gen_step[swap["generation"]] = swap["step"]
                if not wedged and len(gen_step) >= 2:
                    with open(os.environ["DRILL_WEDGE_FILE"], "w") as f:
                        f.write("wedge")
                    wedged = True
                time.sleep(0.1)
            with open(os.environ["DRILL_DONE_FILE"], "w") as f:
                f.write("done")
            hvd_tracing.get_tracer().dump(reason="fleet_drill")

            probes = {}  # generation -> first completed request
            prompts = {req.request_id: (req.prompt, req.max_new_tokens)
                       for _, req in workload}
            for res in results:
                if res.outcome == "completed" and \
                        res.generation not in probes:
                    p, n = prompts[res.request_id]
                    probes[res.generation] = (list(p), n,
                                              list(res.tokens))
            ttfts = sorted(res.ttft_s for res in results
                           if res.ttft_s is not None)
            outcomes = sorted((res.request_id, res.outcome,
                               res.generation) for res in results)
            return (r, sorted(gen_step.items()), lost_box,
                    dict(sub.refusals), probes, ttfts, outcomes)

        env = dict(_ENV)
        env["HVD_FLIGHT_DIR"] = str(tmp_path)
        env["DRILL_PORT"] = str(network.free_port())
        env["DRILL_CKPT"] = ckpt_dir
        env["DRILL_START_FILE"] = traffic_started
        env["DRILL_WEDGE_FILE"] = wedge_now
        env["DRILL_DONE_FILE"] = done_file
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root, os.path.join(repo_root, "tools")] +
            os.environ.get("PYTHONPATH", "").split(os.pathsep))

        import threading

        box = []  # (supervisor, runner) once the trainer is started

        def run_trainer_and_preempt():
            # start the trainer only when traffic is flowing (a slow
            # host's jit warmup must not let it finish unpreempted),
            # then SIGTERM it right after its first publish
            deadline = time.monotonic() + 150.0
            while not os.path.exists(traffic_started) and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            if not os.path.exists(traffic_started):
                return
            sup, runner = hvd_fleet.start_trainer(
                str(tmp_path), ckpt_dir, steps=40, every=3,
                sleep_s=0.3, env=trainer_env)
            box.append((sup, runner))
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                latest = hvd_checkpoint.latest_manifest(ckpt_dir)
                if latest is not None and \
                        int(latest[2].get("generation", 0)) >= 2:
                    break
                time.sleep(0.05)
            os.kill(runner.procs[-1].pid, signal.SIGTERM)

        killer = threading.Thread(target=run_trainer_and_preempt,
                                  daemon=True)
        killer.start()
        try:
            results = run(fn, num_proc=2, env=env, start_timeout_s=180.0)
            killer.join(timeout=180.0)
            assert box, "trainer never started: traffic never began"
            sup, runner = box[0]
            rc = sup.wait(poll_s=0.1)
        finally:
            if box:
                box[0][0].shutdown()

        # the trainer was preempted mid-run and restarted in-slot
        assert rc == 0
        from horovod_tpu.common.exceptions import PREEMPTED_EXIT_CODE
        assert sup.restarts == 1 and len(runner.procs) == 2
        assert runner.procs[0].wait() == PREEMPTED_EXIT_CODE

        by_rank = {x[0]: x for x in results}
        _, gen_step, lost_box, refusals, probes, ttfts, outcomes = \
            by_rank[0]
        gen_step = dict(gen_step)
        # >=2 mid-traffic swaps: three distinct generations served
        assert len(gen_step) >= 3, (
            f"expected >=2 swaps, served generations {gen_step}")
        assert lost_box == [(1,)], lost_box
        assert refusals == {}, refusals
        # zero-drain SLO: every request completed, and stamped with the
        # generation that decoded it; generous CPU-host latency bound
        assert outcomes and all(o == "completed" for _, o, _ in outcomes)
        assert all(g in gen_step for _, _, g in outcomes), outcomes
        assert ttfts and ttfts[-1] < 60.0, ttfts[-5:]

        # temp-0 parity: recompute each probed generation's weights from
        # the trainer's deterministic trajectory and decode solo — a
        # swap that armed the wrong bytes diverges token-for-token here
        from horovod_tpu.serving.engine import ServeEngine
        from horovod_tpu.serving.queue import AdmissionQueue, Request
        for gen, (prompt, n_new, tokens) in sorted(probes.items())[:3]:
            params = hvd_fleet.expected_params(
                params0, gen_step[gen], jax.tree_util.tree_map)
            solo = ServeEngine(
                cfg, params, num_slots=2, max_len=48, kv_block=8,
                queue=AdmissionQueue(max_depth=4,
                                     admission_timeout_s=1e9))
            solo.submit(Request("probe", tuple(prompt),
                                max_new_tokens=n_new))
            (ref,) = solo.run_to_completion()
            assert list(ref.tokens) == tokens, (
                f"generation {gen} (step {gen_step[gen]}) diverged: "
                f"swap armed the wrong weights")

        # the postmortem names every injected event from the dumps alone
        loaded, bad = hvd_postmortem.load_dumps(
            hvd_postmortem.find_dumps(str(tmp_path)))
        assert not bad
        hvd_postmortem.rebase(loaded)
        verdict = hvd_postmortem.analyze(loaded)
        assert verdict["divergent_rank"] == 1, verdict
        swapped_gens = {e.get("generation")
                        for e in verdict["weight_swaps"]}
        assert len(swapped_gens) >= 2, verdict["weight_swaps"]
        assert any(e.get("event") == "ckpt_emergency_exit"
                   for e in verdict["preemptions"]), verdict
        assert any("preempted" in r for r in verdict["reasons"]), \
            verdict["reasons"]
        assert any("swapped to" in r for r in verdict["reasons"]), \
            verdict["reasons"]


# ---------------------------------------------------------------------------
# router-plane drills: the front door under replica loss (2-process,
# real control plane) and a poisoned canary generation (real fleet
# publish -> subscribe -> gate path, per-replica virtual clocks).
# ---------------------------------------------------------------------------

class TestDrillRouterReplicaLost:
    def test_reroute_is_exactly_once_and_postmortem_tells_it(
            self, tmp_path):
        """Drill (j), the router plane: 2 replica processes on the
        negotiation control plane. Rank 0 hosts the front door — a
        Router fronting two real engines, one riding the ReplicaGroup
        as rank 0 and one standing in (locally) for the remote
        replica's serving capacity under replica id 1. Rank 1 wedges
        mid-stream. The coordinator's ledger must turn that silence
        into RanksLostError on replica 0's heartbeat; the engine's
        failover hands the lost ranks to the router, which must requeue
        replica 1's in-flight requests to the survivor EXACTLY once —
        every request completes, the rerouted ones stamped — and the
        postmortem must name both the lost rank and each reroute from
        the dumps alone."""

        def fn():
            import os
            import time
            import jax
            import jax.numpy as jnp
            from horovod_tpu.models import transformer as tr
            from horovod_tpu.router import Router
            from horovod_tpu.serving.engine import ServeEngine
            from horovod_tpu.serving.queue import AdmissionQueue, Request
            from horovod_tpu.serving.replica import ReplicaGroup
            from horovod_tpu.utils import tracing as hvd_tracing

            r = int(os.environ["HVD_PROCESS_ID"])
            port = int(os.environ["DRILL_PORT"])
            done_file = os.environ["DRILL_DONE_FILE"]
            hvd_tracing.reset(enabled=True, rank=r)
            if r == 1:
                group = ReplicaGroup(r, 2, ("127.0.0.1", port),
                                     key=b"k" * 32,
                                     rank_lost_timeout_s=1.5,
                                     start_timeout_s=120.0)
                for _ in range(3):
                    group.heartbeat()
                    time.sleep(0.05)
                deadline = time.monotonic() + 120.0
                while not os.path.exists(done_file) and \
                        time.monotonic() < deadline:
                    time.sleep(0.1)
                group.close(linger_s=0.0)
                return (r, None, None, None, None)

            # rank 0: warm the jit caches BEFORE joining the group
            # (compiles inside would stall heartbeats past the window)
            cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                            attention_impl="full")
            _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
            warm = ServeEngine(
                cfg, params, num_slots=2, max_len=48, kv_block=8,
                queue=AdmissionQueue(max_depth=8,
                                     admission_timeout_s=1e9))
            warm.submit(Request("warm", (3, 1, 4), max_new_tokens=4))
            warm.run_to_completion()

            group = ReplicaGroup(r, 2, ("127.0.0.1", port),
                                 key=b"k" * 32, rank_lost_timeout_s=1.5,
                                 start_timeout_s=120.0)
            lost_box, router_box = [], []

            def on_lost(lost):
                lost_box.append(lost)
                router_box[0].on_ranks_lost(lost)

            def build(replica=None, cb=None):
                return ServeEngine(
                    cfg, params, num_slots=2, max_len=48, kv_block=8,
                    queue=AdmissionQueue(max_depth=32,
                                         admission_timeout_s=1e9),
                    replica=replica, on_ranks_lost=cb)

            router = Router({0: build(group, on_lost), 1: build()},
                            policy="least_loaded", affinity_prefix=0,
                            reroute_window_s=60.0)
            router_box.append(router)
            for i in range(4):
                router.submit(Request(f"pre-{i}", (3, 1, 4),
                                      max_new_tokens=24))
            assigned = dict(router.inflight)
            results = []
            t0 = time.monotonic()
            detect_s = None
            while time.monotonic() - t0 < 60.0:
                results.extend(router.step())
                if lost_box:
                    detect_s = time.monotonic() - t0
                    break
                # pace the decode so pre-* are still mid-stream when
                # the loss lands — there must be work to reroute
                time.sleep(0.15)
            with open(done_file, "w") as f:
                f.write("done")
            # failover must not stop the music: post-loss requests
            # route to the survivor and serve
            for i in range(2):
                router.submit(Request(f"post-{i}", (1, 2),
                                      max_new_tokens=4))
            results.extend(router.run_to_completion())
            # the final dump supersedes the failover's and carries the
            # full event ring: replica_lost, each reroute, completions
            hvd_tracing.get_tracer().dump(reason="router_drill")
            outcomes = sorted((x.request_id, x.outcome, x.replica,
                               x.rerouted) for x in results)
            return (r, detect_s, lost_box, assigned, outcomes)

        env = dict(_ENV)
        env["HVD_FLIGHT_DIR"] = str(tmp_path)
        env["DRILL_PORT"] = str(network.free_port())
        env["DRILL_DONE_FILE"] = str(tmp_path / "victim.done")
        results = run(fn, num_proc=2, env=env, start_timeout_s=180.0)

        by_rank = {x[0]: x for x in results}
        _, detect_s, lost_box, assigned, outcomes = by_rank[0]
        assert detect_s is not None, \
            "replica 0 never detected the wedged peer"
        assert detect_s < 30.0, f"detection took {detect_s:.1f}s"
        assert lost_box == [(1,)], lost_box
        victims = sorted(rid for rid, rep in assigned.items()
                         if rep == 1)
        assert len(victims) == 2, assigned  # the split was 2/2
        # exactly-once: 6 submissions, 6 completions, no duplicates
        assert len(outcomes) == 6 and \
            len({rid for rid, _, _, _ in outcomes}) == 6, outcomes
        assert all(o == "completed" for _, o, _, _ in outcomes)
        # every result was served by the survivor or pre-loss replica 0,
        # and exactly the victims carry the rerouted stamp
        assert all(rep == 0 for _, _, rep, _ in outcomes), outcomes
        assert sorted(rid for rid, _, _, rr in outcomes if rr) == \
            victims, outcomes

        # the postmortem names the lost rank and each reroute
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_postmortem
        loaded, bad = hvd_postmortem.load_dumps(
            hvd_postmortem.find_dumps(str(tmp_path)))
        assert not bad
        hvd_postmortem.rebase(loaded)
        verdict = hvd_postmortem.analyze(loaded)
        assert verdict["divergent_rank"] == 1, verdict
        moves = {(e.get("request_id"), e.get("from_replica"),
                  e.get("to_replica")) for e in verdict["reroutes"]}
        assert moves == {(rid, 1, 0) for rid in victims}, verdict
        assert any("declared lost" in r for r in verdict["reasons"]), \
            verdict["reasons"]
        assert any("rerouted" in r for r in verdict["reasons"]), \
            verdict["reasons"]


class TestDrillCanaryRollback:
    def test_poisoned_generation_rolls_back_fixed_build_promotes(
            self, tmp_path, monkeypatch):
        """Drill (k), the canary state machine end to end on the REAL
        weight path: generation 2 publishes through the fleet plane
        (checkpoint commit -> publisher -> per-replica subscribers),
        the controller claims it, holds the baseline replica's gate,
        and steers the hashed cohort at it. Generation 2 is poisoned —
        its decode steps cost 30x on the serving clock — so the live
        TTFT histograms must breach and auto-roll-back: traffic to 0,
        generation quarantined, zero requests lost, and the quarantined
        replica drained of traffic until generation 3 (the fix) arms,
        canaries cleanly, and promotes fleet-wide.

        Replicas run on per-replica virtual clocks (the engines take a
        ``clock``): two replicas serve in parallel in production, so
        one replica's slow step must not bill the other's TTFT the way
        a serial test loop would. The weights, publish/arm/gate path,
        dispatch, and histogram math are all real."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.fleet import WeightPublisher, WeightSubscriber
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.router import CanaryController, Router
        from horovod_tpu.serving.engine import ServeEngine
        from horovod_tpu.serving.queue import AdmissionQueue, Request
        from horovod_tpu.utils import checkpoint as hvd_checkpoint
        from horovod_tpu.utils import metrics as hvd_metrics
        from horovod_tpu.utils import tracing as hvd_tracing

        monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        hvd_metrics.reset(enabled=True)
        hvd_tracing.reset(enabled=True, rank=0)
        try:
            self._drill(tmp_path, jax, jnp, WeightPublisher,
                        WeightSubscriber, tr, CanaryController, Router,
                        ServeEngine, AdmissionQueue, Request,
                        hvd_checkpoint, hvd_metrics, hvd_tracing)
        finally:
            hvd_metrics.reset()
            hvd_tracing.reset()

    def _drill(self, tmp_path, jax, jnp, WeightPublisher,
               WeightSubscriber, tr, CanaryController, Router,
               ServeEngine, AdmissionQueue, Request, hvd_checkpoint,
               hvd_metrics, hvd_tracing):
        ckpt = str(tmp_path / "ckpt")
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                        attention_impl="full")
        _, params0 = tr.init_params(cfg, jax.random.PRNGKey(0))
        mgr = hvd_checkpoint.CheckpointManager(ckpt, rank=0,
                                               world_size=1,
                                               async_save=False)
        mgr.on_commit = WeightPublisher(ckpt).publish
        mgr.save(params0, step=0, block=True)  # generation 1

        class Clock:
            def __init__(self):
                self.t = 0.0

            def __call__(self):
                return self.t

        clocks = {0: Clock(), 1: Clock()}
        ctrl = CanaryController(pct=50.0, window=6, ttft_x=1.5,
                                goodput_drop=0.10, min_delta_s=0.025,
                                max_canary_replicas=1)
        subs, engines = {}, {}
        for rid in (0, 1):
            subs[rid] = WeightSubscriber(ckpt, like=params0,
                                         replica=rid,
                                         poll_interval_s=0.01)
            boot = subs[rid].load_initial()
            engines[rid] = ServeEngine(
                cfg, boot.params, num_slots=2, max_len=48, kv_block=8,
                queue=AdmissionQueue(max_depth=64,
                                     admission_timeout_s=1e9,
                                     clock=clocks[rid]),
                subscriber=subs[rid], swap_gate=ctrl.gate(rid),
                clock=clocks[rid])

        # per-replica serving time: a healthy step costs 10ms on that
        # replica's clock; a step serving the poisoned generation 2
        # costs 300ms — the regression the canary must catch
        for rid in (0, 1):
            def timed_step(engine=engines[rid], clk=clocks[rid]):
                clk.t += 0.300 if engine.generation == 2 else 0.010
                return type(engine).step(engine)
            engines[rid].step = timed_step
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=0, canary=ctrl)

        submitted, results = [], []

        def pump(n_new, tag, deadline_s=60.0):
            """Feed ``n_new`` requests while stepping the router."""
            i, t0 = 0, time.monotonic()
            while (i < n_new or router.pending()) and \
                    time.monotonic() - t0 < deadline_s:
                if i < n_new:
                    rid = f"{tag}-{i}"
                    assert router.submit(Request(rid, (3, 1, 4),
                                                 max_new_tokens=4))
                    submitted.append(rid)
                    i += 1
                results.extend(router.step())

        # phase 1: steady state on generation 1, both replicas serving
        pump(6, "warm")
        assert ctrl.state == "idle"

        # phase 2: the poisoned build publishes; let the subscribers'
        # background loads ARM it before stepping again, so the tick at
        # the head of the next step claims it while every gate is still
        # closed — then drive traffic until the live histograms decide
        mgr.save(params0, step=1, block=True)  # generation 2
        for rid in (0, 1):
            subs[rid].poll(force=True)
        deadline = time.monotonic() + 60.0
        while any(subs[rid].armed_generation != 2 for rid in (0, 1)) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert all(subs[rid].armed_generation == 2 for rid in (0, 1))
        router.step()  # the tick at its head claims generation 2
        assert ctrl.state == "canary", ctrl.state
        assert ctrl.canary_generation == 2
        (canary_rid,) = ctrl.canary_replicas
        baseline_rid = 1 - canary_rid
        pump(40, "live")
        assert ctrl.state == "rolled_back", ctrl.state
        assert ctrl.quarantined == {2}
        verdict, evidence = ctrl.decisions[-1]
        assert verdict == "rollback"
        assert "ttft_p99" in evidence["breaches"], evidence
        assert evidence["ttft_p99_canary"] > \
            1.5 * evidence["ttft_p99_baseline"], evidence
        # the baseline replica's gate held: it never swapped to the
        # poisoned generation, before the verdict or after
        assert engines[baseline_rid].generation == 1
        assert engines[canary_rid].generation == 2

        # phase 3: post-rollback, the quarantined replica (still
        # serving generation 2 — swaps are monotonic) gets NO traffic
        before = len(results)
        pump(6, "post")
        drained = [x for x in results[before:]
                   if x.request_id.startswith("post-")]
        assert len(drained) == 6
        assert all(x.replica == baseline_rid for x in drained), drained

        # phase 4: the fixed build (generation 3) arms, canaries
        # cleanly, and promotes; the fleet converges on it
        mgr.save(params0, step=2, block=True)  # generation 3
        mgr.close()
        for rid in (0, 1):
            subs[rid].poll(force=True)
        deadline = time.monotonic() + 60.0
        while any(subs[rid].armed_generation != 3 for rid in (0, 1)) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        router.step()
        assert ctrl.state == "canary" and ctrl.canary_generation == 3
        pump(40, "fix")
        assert ctrl.state == "promoted", ctrl.state
        assert ctrl.quarantined == {2}  # the bad build stays banned
        deadline = time.monotonic() + 60.0
        while any(engines[rid].generation != 3 for rid in (0, 1)) \
                and time.monotonic() < deadline:
            router.step()
        assert all(engines[rid].generation == 3 for rid in (0, 1))

        # zero requests lost across the whole incident
        outcomes = {x.request_id: x.outcome for x in results}
        assert sorted(outcomes) == sorted(submitted)
        assert all(o == "completed" for o in outcomes.values())

        # the dumps alone replay both verdicts
        hvd_tracing.get_tracer().dump(reason="canary_drill")
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_postmortem
        loaded, bad = hvd_postmortem.load_dumps(
            hvd_postmortem.find_dumps(str(tmp_path)))
        assert not bad
        hvd_postmortem.rebase(loaded)
        pm = hvd_postmortem.analyze(loaded)
        calls = [(e.get("event"), e.get("generation"))
                 for e in pm["canary_decisions"]]
        assert ("route_rollback", 2) in calls, calls
        assert ("route_promote", 3) in calls, calls
        assert any("ROLLED BACK" in r for r in pm["reasons"]), \
            pm["reasons"]


# ---------------------------------------------------------------------------
# elasticity plane: the supervisor's spawn/drain control door under
# injected transport faults (run/elastic.py ReplicaSupervisorService)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestReplicaSupervisorRPC:
    SPEC = ReplicaSupervisorService.NAME

    def _service(self):
        calls = {"spawn": 0, "drain": []}

        def on_spawn():
            calls["spawn"] += 1
            return 40 + calls["spawn"]

        def on_drain(rid):
            calls["drain"].append(rid)
            return True

        svc = ReplicaSupervisorService(KEY, on_spawn=on_spawn,
                                       on_drain=on_drain)
        return svc, calls

    def test_dropped_response_retries_without_double_spawn(
            self, monkeypatch):
        """drop_response on the spawn ack: the supervisor DID spawn,
        the ack died on the wire, the client's transport retry resends
        the same change_id — and the ledger replays the recorded
        response instead of starting a second replica."""
        monkeypatch.setenv(
            "HVD_CHAOS_SPEC",
            f"{self.SPEC}:ReplicaOpResponse:drop_response:1.0:1")
        monkeypatch.setenv("HVD_CHAOS_SEED", "3")
        svc, calls = self._service()
        try:
            c = ReplicaSupervisorClient(_addr_map(svc.port), KEY)
            c.backoff_base_s = 0.01
            resp = c.spawn_replica("chg-1")
            assert sum(svc._chaos.stats().values()) == 1  # fault fired
            assert resp.ok and resp.replica_id == 41
            assert resp.duplicate  # the retry was served from the ledger
            assert calls["spawn"] == 1  # executed exactly once
            c.close()
        finally:
            svc.shutdown()

    def test_duplicated_drain_is_idempotent(self, monkeypatch):
        """Network-level duplicate delivery of a DrainReplicaRequest:
        the handler runs twice, the drain hook runs once."""
        monkeypatch.setenv(
            "HVD_CHAOS_SPEC",
            f"{self.SPEC}:DrainReplicaRequest:dup_request:1.0:1")
        svc, calls = self._service()
        try:
            c = ReplicaSupervisorClient(_addr_map(svc.port), KEY)
            resp = c.drain_replica("chg-2", 1)
            assert sum(svc._chaos.stats().values()) == 1
            assert resp.ok and resp.replica_id == 1
            assert calls["drain"] == [1]  # once, not twice
            c.close()
        finally:
            svc.shutdown()

    def test_delayed_drain_completes_within_bound(self, monkeypatch):
        monkeypatch.setenv(
            "HVD_CHAOS_SPEC",
            f"{self.SPEC}:DrainReplicaRequest:delay_request:1.0:1")
        monkeypatch.setenv("HVD_CHAOS_DELAY_MS", "200")
        svc, calls = self._service()
        try:
            c = ReplicaSupervisorClient(_addr_map(svc.port), KEY)
            t0 = time.monotonic()
            resp = c.drain_replica("chg-3", 2)
            elapsed = time.monotonic() - t0
            assert resp.ok and calls["drain"] == [2]
            assert 0.15 <= elapsed < 10.0  # delayed, not hung
            c.close()
        finally:
            svc.shutdown()

    def test_distinct_change_ids_execute_separately(self):
        svc, calls = self._service()
        try:
            c = ReplicaSupervisorClient(_addr_map(svc.port), KEY)
            a = c.spawn_replica("chg-a")
            b = c.spawn_replica("chg-b")
            again = c.spawn_replica("chg-a")
            assert (a.replica_id, b.replica_id) == (41, 42)
            assert again.replica_id == 41 and again.duplicate
            assert calls["spawn"] == 2
            c.close()
        finally:
            svc.shutdown()

    def test_hook_exception_fails_loud_by_name(self):
        def bad_spawn():
            raise RuntimeError("no capacity on any host")

        svc = ReplicaSupervisorService(KEY, on_spawn=bad_spawn)
        try:
            c = ReplicaSupervisorClient(_addr_map(svc.port), KEY)
            resp = c.spawn_replica("chg-x")
            assert not resp.ok
            assert "no capacity" in resp.detail  # the NAMED failure
            # the failure is ledgered too: a retry must not re-execute
            # a spawn that already failed loudly
            assert c.spawn_replica("chg-x").duplicate
            c.close()
        finally:
            svc.shutdown()

    def test_unconfigured_hooks_refuse(self):
        svc = ReplicaSupervisorService(KEY)
        try:
            c = ReplicaSupervisorClient(_addr_map(svc.port), KEY)
            assert not c.spawn_replica("c1").ok
            assert not c.drain_replica("c2", 0).ok
            c.close()
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# elasticity plane drills: planned scale-down with in-flight work,
# flap-storm convergence + graded rollback, and breaker isolation of a
# wedged-but-heartbeating replica (router/elastic.py, docs/elasticity.md)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestDrillElasticity:
    """Drills (l), the elasticity plane end to end on REAL serving
    engines: the ElasticityController rides ``Router.step()`` exactly
    as in production, engines run on a shared virtual clock (each
    engine step bills 10ms), and every verdict must be replayable from
    the flight dumps alone."""

    class _Clock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    def _engine(self, clock, cfg, params, num_slots):
        from horovod_tpu.serving.engine import ServeEngine
        from horovod_tpu.serving.queue import AdmissionQueue

        eng = ServeEngine(
            cfg, params, num_slots=num_slots, max_len=64, kv_block=8,
            queue=AdmissionQueue(max_depth=64, admission_timeout_s=1e9,
                                 clock=clock),
            clock=clock)

        def timed_step(engine=eng, clk=clock):
            clk.t += 0.010
            return type(engine).step(engine)

        eng.step = timed_step
        return eng

    def _postmortem(self, tmp_path, hvd_tracing, reason):
        hvd_tracing.get_tracer().dump(reason=reason)
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_postmortem
        loaded, bad = hvd_postmortem.load_dumps(
            hvd_postmortem.find_dumps(str(tmp_path)))
        assert not bad
        hvd_postmortem.rebase(loaded)
        return hvd_postmortem.analyze(loaded)

    def test_planned_scale_down_drains_clean_with_exact_parity(
            self, tmp_path, monkeypatch):
        """The planned scale-down drill: two replicas each hold an
        in-flight decode when the operator lowers the floor; the
        controller drains the victim gracefully — its in-flight work
        finishes on it, nothing is killed, nothing is double-delivered
        — grades the shrunk fleet like a canary, promotes, and the
        postmortem names every transition from the dumps alone."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.router import Router
        from horovod_tpu.router.elastic import ElasticityController
        from horovod_tpu.serving.queue import Request
        from horovod_tpu.utils import metrics as hvd_metrics
        from horovod_tpu.utils import tracing as hvd_tracing

        monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        hvd_metrics.reset(enabled=True)
        hvd_tracing.reset(enabled=True, rank=0)
        try:
            clock = self._Clock()
            cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                            attention_impl="full")
            _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
            engines = {rid: self._engine(clock, cfg, params, 4)
                       for rid in (0, 1)}

            def spawn(router):
                rid = max(router._handles) + 1
                return router.add_replica(
                    rid, self._engine(clock, cfg, params, 4)).replica_id

            # min_replicas=2 holds the floor through warm-up (idle is
            # allowed to accumulate dwell, but the floor blocks action)
            ctrl = ElasticityController(
                spawn=spawn, min_replicas=2, dwell_s=0.2, cooldown_s=2.0,
                window=6, ttft_x=1.5, min_delta_s=0.5, up_depth=100.0,
                down_util=0.25, clock=clock)
            router = Router(engines, policy="least_loaded",
                            affinity_prefix=0, elastic=ctrl, shed_depth=0,
                            drain_timeout_s=60.0, clock=clock)
            submitted, results = [], []

            def pump(n_new, tag, max_tokens=2, steps_cap=2000):
                i, steps = 0, 0
                while (i < n_new or router.pending()) and \
                        steps < steps_cap:
                    if i < n_new:
                        rid = f"{tag}-{i}"
                        assert router.submit(
                            Request(rid, (3, 1, 4),
                                    max_new_tokens=max_tokens))
                        submitted.append(rid)
                        i += 1
                    results.extend(router.step())
                    steps += 1

            # phase 1: steady traffic fills the controller's baseline
            pump(6, "warm")
            assert ctrl.state == "steady"
            assert router.live_replicas() == [0, 1]

            # phase 2: one long decode IN FLIGHT on each replica — the
            # work a graceless scale-down would kill
            for i in range(2):
                rid = f"hold-{i}"
                assert router.submit(Request(rid, (3, 1, 4),
                                             max_new_tokens=16))
                submitted.append(rid)
                results.extend(router.step())
            assert sorted(set(router.inflight.values())) == [0, 1]

            # phase 3: the operator lowers the floor; idle has already
            # dwelled, so the next tick executes the planned scale-down
            ctrl.min_replicas = 1
            guard = 0
            while ctrl.state == "steady" and guard < 200:
                results.extend(router.step())
                guard += 1
            assert ctrl.state == "grading"
            assert ctrl.transitions[-1]["action"] == "scale_down"
            victim = ctrl.transitions[-1]["replica"]
            assert victim in router._draining
            # the victim was mid-decode when the drain began
            assert any(r == victim for r in router.inflight.values())

            # phase 4: the drain runs to completion — in-flight work
            # retires ON the victim, which then leaves the fleet
            guard = 0
            while router._draining and guard < 1000:
                results.extend(router.step())
                guard += 1
            assert not router._draining
            assert router.live_replicas() == [1 - victim]
            # the survivor's own long decode may still be running —
            # only the VICTIM's work had to finish before retirement
            guard = 0
            while router.pending() and guard < 1000:
                results.extend(router.step())
                guard += 1
            hold = {r.request_id: r for r in results
                    if r.request_id.startswith("hold-")}
            assert len(hold) == 2
            assert all(r.outcome == "completed" for r in hold.values())
            assert any(r.replica == victim for r in hold.values())

            # phase 5: the after-window fills on the shrunk fleet and
            # the change grades like a weight rollout: promote
            pump(6, "post")
            guard = 0
            while ctrl.state == "grading" and guard < 100:
                results.extend(router.step())
                guard += 1
            assert ctrl.state == "steady"
            verdict, evidence = ctrl.decisions[-1]
            assert verdict == "promote"
            assert evidence["action"] == "scale_down"
            assert evidence["breaches"] == []

            # zero lost requests, exact submission/completion parity
            assert len(results) == len(submitted)
            outcomes = {r.request_id: r.outcome for r in results}
            assert sorted(outcomes) == sorted(submitted)
            assert all(o == "completed" for o in outcomes.values())

            # the dumps alone name the transitions
            pm = self._postmortem(tmp_path, hvd_tracing,
                                  "elastic_scale_down_drill")
            acts = [(t["action"], t.get("replica"))
                    for t in pm["elastic_transitions"]]
            assert ("scale_down", victim) in acts, acts
            assert ("promote", victim) in acts, acts
            drains = [(e.get("event"), e.get("replica"))
                      for e in pm["drain_events"]]
            assert ("route_drain_begin", victim) in drains, drains
            assert ("route_drain_done", victim) in drains, drains
            assert not any(e == "route_drain_timeout"
                           for e, _ in drains), drains
            assert any("drained clean" in r for r in pm["reasons"]), \
                pm["reasons"]
            assert any("scale_down" in r for r in pm["reasons"]), \
                pm["reasons"]
        finally:
            hvd_metrics.reset()
            hvd_tracing.reset()

    def test_flap_storm_converges_and_bad_scale_down_rolls_back(
            self, tmp_path, monkeypatch):
        """The flap-storm drill: eight load oscillations faster than
        the dwell produce ZERO topology changes; a genuine lull then
        scales down — and when the next storm proves the shrunk fleet
        breaches the TTFT SLO, the grade rolls the scale-down back by
        re-spawning, after which the fleet converges and stays put."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.router import Router
        from horovod_tpu.router.elastic import ElasticityController
        from horovod_tpu.serving.queue import Request
        from horovod_tpu.utils import metrics as hvd_metrics
        from horovod_tpu.utils import tracing as hvd_tracing

        monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        hvd_metrics.reset(enabled=True)
        hvd_tracing.reset(enabled=True, rank=0)
        try:
            clock = self._Clock()
            cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                            attention_impl="full")
            _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
            engines = {rid: self._engine(clock, cfg, params, 2)
                       for rid in (0, 1)}
            spawned = []

            def spawn(router):
                rid = max(router._handles) + 1
                spawned.append(rid)
                return router.add_replica(
                    rid, self._engine(clock, cfg, params, 2)).replica_id

            ctrl = ElasticityController(
                spawn=spawn, min_replicas=1, dwell_s=0.3, cooldown_s=0.5,
                window=6, ttft_x=1.5, min_delta_s=0.025, up_depth=100.0,
                down_util=0.2, clock=clock)
            router = Router(engines, policy="least_loaded",
                            affinity_prefix=0, elastic=ctrl, shed_depth=0,
                            drain_timeout_s=60.0, clock=clock)
            submitted, results = [], []

            def pump(n_new, tag, max_tokens=4, steps_cap=2000):
                i, steps = 0, 0
                while (i < n_new or router.pending()) and \
                        steps < steps_cap:
                    if i < n_new:
                        rid = f"{tag}-{i}"
                        assert router.submit(
                            Request(rid, (3, 1, 4),
                                    max_new_tokens=max_tokens))
                        submitted.append(rid)
                        i += 1
                    results.extend(router.step())
                    steps += 1

            # phase 1, the flap storm: 8 oscillations, each lull far
            # shorter than the dwell — hysteresis must absorb ALL of it
            for cycle in range(8):
                pump(4, f"flap{cycle}")
                for _ in range(3):  # ~60ms lull << 300ms dwell
                    results.extend(router.step())
            assert ctrl.state == "steady"
            assert ctrl.transitions == []  # not one flap leaked through
            assert router.live_replicas() == [0, 1]

            # phase 2, a real lull: idle holds past the dwell and the
            # controller drains one replica
            guard = 0
            while ctrl.state == "steady" and guard < 200:
                results.extend(router.step())
                guard += 1
            assert ctrl.state == "grading"
            assert ctrl.transitions[-1]["action"] == "scale_down"
            victim = ctrl.transitions[-1]["replica"]
            guard = 0
            while router._draining and guard < 200:
                results.extend(router.step())
                guard += 1
            assert router.live_replicas() == [1 - victim]

            # phase 3, the storm returns on the shrunk fleet: a 16-deep
            # burst queues behind the survivor's two slots, the
            # after-window breaches TTFT vs the flap-era baseline and
            # the scale-down ROLLS BACK by re-spawning
            for i in range(16):
                rid = f"storm-{i}"
                assert router.submit(Request(rid, (3, 1, 4),
                                             max_new_tokens=8))
                submitted.append(rid)
            guard = 0
            while ctrl.state == "grading" and guard < 500:
                results.extend(router.step())
                guard += 1
            verdict, evidence = ctrl.decisions[-1]
            assert verdict == "rollback", ctrl.decisions
            assert "ttft_p99" in evidence["breaches"], evidence
            assert evidence["ttft_p99_after"] > \
                1.5 * evidence["ttft_p99_baseline"], evidence
            assert spawned, "rollback must re-spawn what was drained"
            assert len(router.live_replicas()) == 2

            # phase 4, convergence: steady trickle, no further changes
            changes = len(ctrl.transitions)
            pump(12, "settle", max_tokens=2)
            assert len(ctrl.transitions) == changes
            assert ctrl.state == "steady"
            assert len(router.live_replicas()) == 2

            # zero lost requests across every phase of the storm
            assert len(results) == len(submitted)
            outcomes = {r.request_id: r.outcome for r in results}
            assert sorted(outcomes) == sorted(submitted)
            assert all(o == "completed" for o in outcomes.values())

            # the dumps replay the whole storm
            pm = self._postmortem(tmp_path, hvd_tracing,
                                  "elastic_flap_drill")
            acts = [t["action"] for t in pm["elastic_transitions"]]
            assert acts.count("scale_down") == 1, acts
            assert acts.count("rollback") == 1, acts
            assert any("ROLLED BACK" in r for r in pm["reasons"]), \
                pm["reasons"]
        finally:
            hvd_metrics.reset()
            hvd_tracing.reset()

    def test_breaker_isolates_wedged_but_heartbeating_replica(
            self, tmp_path, monkeypatch):
        """The sick-but-alive drill: a replica keeps serving fresh load
        snapshots (its heartbeat is fine) but stops finishing work
        mid-decode. The circuit breaker must trip on the wedged
        in-flight age within its timeout bound, steer ALL new traffic
        to the healthy replica while open, and close again once the
        replica recovers — with every request eventually completing."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.router import Router
        from horovod_tpu.router.elastic import CircuitBreaker
        from horovod_tpu.serving.queue import Request
        from horovod_tpu.utils import metrics as hvd_metrics
        from horovod_tpu.utils import tracing as hvd_tracing

        monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        hvd_metrics.reset(enabled=True)
        hvd_tracing.reset(enabled=True, rank=0)
        try:
            clock = self._Clock()
            cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                            attention_impl="full")
            _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
            engines = {rid: self._engine(clock, cfg, params, 2)
                       for rid in (0, 1)}
            breaker = CircuitBreaker(fails=3, probe_s=0.5, close_n=1,
                                     timeout_s=1.0, clock=clock)
            router = Router(engines, policy="least_loaded",
                            affinity_prefix=0, breaker=breaker,
                            shed_depth=0, clock=clock)
            submitted, results = [], []

            def feed(tag, n, max_tokens=2):
                ids = set()
                for i in range(n):
                    rid = f"{tag}-{i}"
                    assert router.submit(Request(rid, (3, 1, 4),
                                                 max_new_tokens=max_tokens))
                    submitted.append(rid)
                    ids.add(rid)
                    results.extend(router.step())
                return ids

            def drive(want, max_steps=600):
                done = {r.request_id for r in results}
                for _ in range(max_steps):
                    if want <= done:
                        return
                    for r in router.step():
                        results.append(r)
                        done.add(r.request_id)
                assert want <= done, f"never finished: {want - done}"

            drive(feed("warm", 4))

            # one long decode on each replica, then wedge the one
            # serving hold-1: step() stops making progress while
            # load_snapshot stays perfectly fresh (the router stamps
            # fronted engines' snapshots 'now' — heartbeat looks fine)
            feed("hold", 2, max_tokens=32)
            wedged = router.inflight["hold-1"]
            healthy = 1 - wedged
            real_step = engines[wedged].step
            engines[wedged].step = lambda: []
            t_wedge = clock.t

            guard = 0
            while breaker.state(wedged) != "open" and guard < 500:
                results.extend(router.step())
                guard += 1
            assert breaker.state(wedged) == "open"
            # bounded isolation: the trip lands within the wedge
            # timeout plus scheduler granularity
            assert clock.t - t_wedge <= breaker.timeout_s + 0.25, \
                (clock.t, t_wedge)
            # ...while its heartbeat never went stale
            assert router.loads()[wedged]["ts"] == clock.t

            # while open, every new request lands on the healthy
            # replica (probe timer hasn't fired yet)
            before = len(results)
            iso = feed("iso", 4)
            drive(iso)
            served = [r for r in results[before:]
                      if r.request_id in iso]
            assert len(served) == 4
            assert all(r.replica == healthy for r in served), served

            # recovery: the replica unwedges, its stuck decode retires,
            # and that success closes the breaker (close_n=1)
            engines[wedged].step = real_step
            drive({"hold-0", "hold-1"})
            assert breaker.state(wedged) == "closed"
            # submit the batch before stepping: queue-depth feedback
            # must spread it across BOTH replicas again
            back = set()
            for i in range(4):
                rid = f"back-{i}"
                assert router.submit(Request(rid, (3, 1, 4),
                                             max_new_tokens=2))
                submitted.append(rid)
                back.add(rid)
            drive(back)
            assert any(r.replica == wedged for r in results
                       if r.request_id in back)

            # exact parity: the wedge delayed work, it lost none
            assert len(results) == len(submitted)
            outcomes = {r.request_id: r.outcome for r in results}
            assert sorted(outcomes) == sorted(submitted)
            assert all(o == "completed" for o in outcomes.values())

            pm = self._postmortem(tmp_path, hvd_tracing,
                                  "elastic_breaker_drill")
            moves = [(e.get("replica"), e.get("state"), e.get("reason"))
                     for e in pm["breaker_transitions"]]
            assert (wedged, "open", "wedged") in moves, moves
            assert (wedged, "closed", "recovered") in moves, moves
            assert any("tripped open (wedged)" in r
                       for r in pm["reasons"]), pm["reasons"]
        finally:
            hvd_metrics.reset()
            hvd_tracing.reset()


# ---------------------------------------------------------------------------
# alerting & run-history plane drill: KV-pressure overload burns the
# goodput budget, the alert fires inside its for-duration bound with a
# durable incident, resolves once load drops, and the postmortem names
# the whole episode from dumps alone (utils/alerts.py, docs/alerts.md)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestDrillAlertPlane:
    """Drills (m), the alerting plane end to end on a REAL serving
    engine: the AlertManager rides ``ServeEngine.step()`` exactly as in
    production (no drill-only control loop), the engine runs on a
    virtual clock (each step bills 250ms so the 60s/15s burn windows
    cost hundreds of steps, not wall-minutes), and the episode must be
    replayable from the flight dumps and the incident file alone."""

    class _Clock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    def _engine(self, clock, cfg, params, num_slots):
        from horovod_tpu.serving.engine import ServeEngine
        from horovod_tpu.serving.queue import AdmissionQueue

        eng = ServeEngine(
            cfg, params, num_slots=num_slots, max_len=64, kv_block=8,
            queue=AdmissionQueue(max_depth=64, admission_timeout_s=1e9,
                                 clock=clock),
            clock=clock)

        def timed_step(engine=eng, clk=clock):
            clk.t += 0.250
            return type(engine).step(engine)

        eng.step = timed_step
        return eng

    def _postmortem(self, tmp_path, hvd_tracing, reason):
        hvd_tracing.get_tracer().dump(reason=reason)
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import hvd_postmortem
        loaded, bad = hvd_postmortem.load_dumps(
            hvd_postmortem.find_dumps(str(tmp_path)))
        assert not bad
        hvd_postmortem.rebase(loaded)
        return hvd_postmortem.analyze(loaded)

    def test_kv_pressure_fires_goodput_burn_and_resolves(
            self, tmp_path, monkeypatch):
        """The KV-pressure drill: a healthy baseline, then an overload
        whose requests blow their deadlines mid-decode — every one of
        their tokens becomes wasted work, both burn windows go hot, and
        ``serve_goodput_burn`` walks pending -> firing inside its
        for-duration bound. The incident file names the dominant serve
        phase and the requests stranded in slots at capture time; once
        the overload stops the alert resolves through the clear-hold;
        and the postmortem names the incident from dumps alone."""
        import json as _json

        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.serving.queue import Request
        from horovod_tpu.utils import alerts as hvd_alerts
        from horovod_tpu.utils import history as hvd_history
        from horovod_tpu.utils import metrics as hvd_metrics
        from horovod_tpu.utils import tracing as hvd_tracing

        flight_dir = tmp_path / "flight"
        hist_dir = tmp_path / "hist"
        monkeypatch.setenv("HVD_FLIGHT_DIR", str(flight_dir))
        monkeypatch.setenv("HVD_HISTORY_DIR", str(hist_dir))
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        hvd_metrics.reset(enabled=True)
        hvd_tracing.reset(enabled=True, rank=0)
        hvd_history.reset(enabled=True, dirpath=str(hist_dir), rank=0,
                          interval_s=2.0)
        mgr = hvd_alerts.reset(enabled=True)
        rule = next(r for r in mgr.rules
                    if r.name == "serve_goodput_burn")
        try:
            clock = self._Clock()
            cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                            attention_impl="full")
            _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
            eng = self._engine(clock, cfg, params, 4)
            results = []

            def state():
                return mgr.states()["serve_goodput_burn"]["state"]

            # phase 1: ~70 virtual seconds of healthy traffic — every
            # request completes, the burn windows fill with goodput.
            i = 0
            while clock.t < 70.0:
                if len(eng.queue) < 2:
                    eng.submit(Request(f"warm-{i}", (3, 1, 4),
                                       max_new_tokens=2))
                    i += 1
                results.extend(eng.step())
            assert state() == "inactive"

            # phase 2: KV-pressure overload — slots saturate with
            # decodes that blow staggered sub-second deadlines, so
            # every admitted token is wasted work, by reason, and at
            # any instant some requests sit admitted-but-unretired.
            t_pending = t_firing = None
            j = 0
            guard = 0
            while t_firing is None and guard < 400:
                while len(eng.queue) < 4:
                    eng.submit(Request(f"kv-{j}", (3, 1, 4),
                                       max_new_tokens=16,
                                       deadline_s=0.3 + 0.2 * (j % 3)))
                    j += 1
                results.extend(eng.step())
                s = state()
                if t_pending is None and s in ("pending", "firing"):
                    t_pending = clock.t
                if s == "firing":
                    t_firing = clock.t
                guard += 1
            assert t_firing is not None, "goodput burn never fired"
            # the for-duration hysteresis held: not a same-tick page,
            # and firing landed within the bound (for_s plus one alert
            # interval plus one step of tick granularity).
            assert t_firing - t_pending >= rule.for_s
            assert t_firing - t_pending <= rule.for_s + \
                mgr.interval_s + 0.250 + 1e-6
            ev = mgr.states()["serve_goodput_burn"]["evidence"]
            assert ev["burn_60s"] >= ev["threshold"]
            assert ev["burn_15s"] >= ev["threshold"]

            # the incident file: dominant phase + stranded requests
            incidents = [p for p in mgr.incidents
                         if "serve_goodput_burn" in p]
            assert len(incidents) == 1
            with open(incidents[0]) as f:
                inc = _json.load(f)
            assert inc["alert"] == "serve_goodput_burn"
            assert inc["severity"] == "page"
            assert inc["dominant_phase"] is not None
            assert inc["stranded_request_ids"], \
                "overload left no admitted-but-unretired requests?"
            assert all(r.startswith("kv-")
                       for r in inc["stranded_request_ids"])
            assert inc["history"], "incident carries no WAL slice"
            assert inc["manifest"] is not None

            # phase 3: the overload stops; the engine drains, the short
            # window cools, and the alert resolves through clear_s.
            guard = 0
            while state() == "firing" and guard < 400:
                if len(eng.queue) < 2:
                    eng.submit(Request(f"cool-{j}", (3, 1, 4),
                                       max_new_tokens=2))
                    j += 1
                results.extend(eng.step())
                guard += 1
            assert state() == "inactive"
            assert "serve_goodput_burn" not in mgr.firing()

            # the dumps alone name the episode: the firing escalation
            # already dumped once (reason=alert:serve_goodput_burn);
            # the postmortem reads those plus a final dump.
            pm = self._postmortem(flight_dir, hvd_tracing,
                                  "alert_plane_drill")
            trans = [(t["alert"], t["transition"])
                     for t in pm["alert_transitions"]]
            assert ("serve_goodput_burn", "pending") in trans, trans
            assert ("serve_goodput_burn", "firing") in trans, trans
            assert ("serve_goodput_burn", "resolved") in trans, trans
            assert any(i["alert"] == "serve_goodput_burn"
                       for i in pm["incidents"])
            assert any("incident for 'serve_goodput_burn'" in r
                       for r in pm["reasons"]), pm["reasons"]
        finally:
            hvd_alerts.reset(enabled=False)
            hvd_history.reset(enabled=False)
            hvd_metrics.reset()
            hvd_tracing.reset()
