"""Roofline cost model (utils/costmodel.py): chip table lookup,
analytic FLOPs/bytes vs hand-computed values for a small LM config,
per-class verdicts, and the achievable-MFU decomposition."""

import math
import types

import pytest

from horovod_tpu.utils import costmodel


def _cfg(num_layers=2, d_model=8, d_ff=16, vocab_size=32):
    return types.SimpleNamespace(num_layers=num_layers, d_model=d_model,
                                 d_ff=d_ff, vocab_size=vocab_size)


# hand-computed for the _cfg defaults:
# p_matmul = 2*(4*8^2 + 3*8*16) + 8*32 = 2*(256+384) + 256 = 1536
P_MATMUL = 1536


class TestChipSpec:
    def test_longest_prefix_wins(self):
        assert costmodel.chip_spec("TPU v5 lite").peak_flops == 197e12
        assert costmodel.chip_spec("TPU v5").peak_flops == 459e12
        assert costmodel.chip_spec("TPU v5p").peak_flops == 459e12
        assert costmodel.chip_spec("TPU v4").peak_flops == 275e12

    def test_device_object_and_unknown(self):
        dev = types.SimpleNamespace(device_kind="TPU v6e")
        assert costmodel.chip_spec(dev).peak_flops == 918e12
        assert costmodel.chip_spec("GPU A100") is None
        assert costmodel.chip_spec(None) is None

    def test_peak_flops_none_for_cpu_and_unknown(self):
        # cpu has a spec row (CI exercises the full path) but no
        # meaningful MFU denominator
        assert costmodel.chip_spec("cpu") is not None
        assert costmodel.peak_flops("cpu") is None
        assert costmodel.peak_flops("GPU A100") is None
        assert costmodel.peak_flops("TPU v4") == 275e12

    def test_ridge_point(self):
        spec = costmodel.ChipSpec("t", 200e12, 1e12, 1e11)
        assert spec.ridge_flops_per_byte == pytest.approx(200.0)


class TestProgramCosts:
    def test_dict_and_list_forms(self):
        ca = {"flops": 10.0, "bytes accessed": 4.0}
        c = types.SimpleNamespace(cost_analysis=lambda: ca)
        assert costmodel.program_costs(c) == {"flops": 10.0, "bytes": 4.0}
        c = types.SimpleNamespace(cost_analysis=lambda: [ca])
        assert costmodel.program_costs(c) == {"flops": 10.0, "bytes": 4.0}

    def test_missing_or_failing(self):
        c = types.SimpleNamespace(
            cost_analysis=lambda: (_ for _ in ()).throw(RuntimeError()))
        assert costmodel.program_costs(c) is None
        c = types.SimpleNamespace(cost_analysis=lambda: [])
        assert costmodel.program_costs(c) is None
        c = types.SimpleNamespace(cost_analysis=lambda: {"other": 1})
        assert costmodel.program_costs(c) is None


class TestAnalyticLMCosts:
    def test_matches_transformer_convention(self):
        # the model's P_matmul must be THE p_matmul of the headline MFU
        from horovod_tpu.models import transformer as tr
        cfg = tr.TransformerConfig()
        seq = 128
        assert (6 * costmodel.lm_matmul_params(cfg) +
                12 * cfg.num_layers * seq * cfg.d_model ==
                tr.matmul_flops_per_token(cfg, seq))

    def test_hand_computed_small_config(self):
        # seq=4, batch_per_chip=3 → 12 tokens; 4 chips → ring 3/4
        costs = costmodel.analytic_lm_costs(_cfg(), seq=4,
                                            batch_per_chip=3, n_chips=4)
        assert costmodel.lm_matmul_params(_cfg()) == P_MATMUL
        assert costs["matmul"]["flops"] == 6 * P_MATMUL * 12       # 110592
        assert costs["matmul"]["hbm_bytes"] == 3 * P_MATMUL * 2    # 9216
        assert costs["matmul"]["wire_bytes"] == 0.0
        assert costs["attention"]["flops"] == 12 * 2 * 4 * 8 * 12  # 9216
        assert costs["attention"]["hbm_bytes"] == 10 * 2 * 12 * 8 * 2
        assert costs["collective"]["flops"] == 0.0
        assert costs["collective"]["wire_bytes"] == pytest.approx(
            2 * P_MATMUL * 2.0 * 0.75)                             # 4608
        assert costs["collective"]["hbm_bytes"] == 2 * P_MATMUL * 2

    def test_single_chip_has_no_wire(self):
        costs = costmodel.analytic_lm_costs(_cfg(), seq=4,
                                            batch_per_chip=3, n_chips=1)
        assert costs["collective"]["wire_bytes"] == 0.0
        assert costs["collective"]["hbm_bytes"] == 0.0

    def test_int8_wire_width_halves_bytes(self):
        bf16 = costmodel.analytic_lm_costs(_cfg(), 4, 3, n_chips=4)
        int8 = costmodel.analytic_lm_costs(_cfg(), 4, 3, n_chips=4,
                                           wire_bytes_per_param=1.0)
        assert int8["collective"]["wire_bytes"] == pytest.approx(
            bf16["collective"]["wire_bytes"] / 2)


SPEC = costmodel.ChipSpec("test", 1e6, 1e6, 1e5)


class TestRoofline:
    def test_verdicts_and_bounds(self):
        costs = costmodel.analytic_lm_costs(_cfg(), 4, 3, n_chips=4)
        rl = costmodel.roofline(costs, SPEC)
        # matmul: 110592 flops / 1e6 = 110.592 ms compute vs 9.216 mem
        assert rl["matmul"]["verdict"] == "compute-bound"
        assert rl["matmul"]["bound_ms"] == pytest.approx(110.592)
        assert rl["matmul"]["arith_intensity"] == pytest.approx(12.0)
        assert rl["matmul"]["ridge_flops_per_byte"] == pytest.approx(1.0)
        assert rl["attention"]["verdict"] == "compute-bound"
        assert rl["attention"]["bound_ms"] == pytest.approx(9.216)
        # collective: 4608 wire bytes / 1e5 = 46.08 ms > 6144/1e6 hbm
        assert rl["collective"]["verdict"] == "comm-bound"
        assert rl["collective"]["bound_ms"] == pytest.approx(46.08)
        assert rl["collective"]["arith_intensity"] == pytest.approx(0.0)

    def test_memory_bound_class(self):
        rl = costmodel.roofline(
            {"copyish": {"flops": 10.0, "hbm_bytes": 1e6}}, SPEC)
        assert rl["copyish"]["verdict"] == "memory-bound"
        assert rl["copyish"]["bound_ms"] == pytest.approx(1000.0)


class TestMFUDecomposition:
    COSTS = None

    def setup_method(self):
        self.costs = costmodel.analytic_lm_costs(_cfg(), 4, 3, n_chips=4)

    def test_measured_vs_roofline(self):
        dec = costmodel.mfu_decomposition(200.0, self.costs, SPEC)
        # total flops 119808; roofline_ms = 110.592+9.216+46.08
        assert dec["flops_per_step"] == pytest.approx(119808)
        assert dec["roofline_ms_per_step"] == pytest.approx(165.888)
        assert dec["measured_mfu"] == pytest.approx(0.599, abs=1e-3)
        assert dec["roofline_mfu"] == pytest.approx(0.7222, abs=1e-3)
        assert dec["mfu_gap"] == pytest.approx(
            dec["roofline_mfu"] - dec["measured_mfu"], abs=1e-4)

    def test_gap_attribution_by_class(self):
        by_class = {"matmul": 120.0, "attention": 12.0,
                    "collective": 50.0}
        dec = costmodel.mfu_decomposition(200.0, self.costs, SPEC,
                                          measured_ms_by_class=by_class)
        gap = dec["gap_by_class"]
        # excess: matmul 9.408, attention 2.784, collective 3.92,
        # residual 200-182=18 → shares of the total gap
        total_excess = 9.408 + 2.784 + 3.92 + 18.0
        assert gap["matmul"] == pytest.approx(
            dec["mfu_gap"] * 9.408 / total_excess, abs=1e-4)
        assert gap["residual"] == pytest.approx(
            dec["mfu_gap"] * 18.0 / total_excess, abs=1e-4)
        assert sum(gap.values()) == pytest.approx(dec["mfu_gap"],
                                                  abs=1e-3)

    def test_zero_measured_ms_guarded(self):
        dec = costmodel.mfu_decomposition(0.0, self.costs, SPEC)
        assert dec["measured_mfu"] is None
        assert "mfu_gap" not in dec


class TestMeasuredClassMs:
    def test_folds_profile_classes(self):
        dec = {"classes": [
            {"class": "flash_fwd", "ms_per_step": 1.0},
            {"class": "flash_dq", "ms_per_step": 2.0},
            {"class": "flash_dkv", "ms_per_step": 3.0},
            {"class": "matmul", "ms_per_step": 10.0},
            {"class": "collective", "ms_per_step": 4.0},
            {"class": "copy", "ms_per_step": 0.5},
            {"class": "fusion", "ms_per_step": 0.5},
        ]}
        ms = costmodel.measured_class_ms(dec)
        assert ms == {"attention": 6.0, "matmul": 10.0,
                      "collective": 4.0, "other": 1.0}

    def test_empty(self):
        assert costmodel.measured_class_ms(None) == {}
        assert costmodel.measured_class_ms({}) == {}


class TestLMAttribution:
    def test_end_to_end_wrapper(self):
        dec = {"classes": [{"class": "matmul", "ms_per_step": 120.0},
                           {"class": "collective", "ms_per_step": 50.0}]}
        out = costmodel.lm_attribution(_cfg(), 4, 3, SPEC, 200.0,
                                       decomposition=dec, n_chips=4)
        assert out["chip"]["kind"] == "test"
        assert out["n_chips"] == 4
        assert out["classes"]["collective"]["verdict"] == "comm-bound"
        assert out["measured_mfu"] is not None
        assert "gap_by_class" in out
