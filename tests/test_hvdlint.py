"""The analyzer analyzed: per-rule trigger / non-trigger / suppression
fixtures for tools/hvdlint, plus the end-to-end gate asserting the repo
itself lints clean (zero unbaselined findings — the same invocation CI
runs first).

Fixture snippets are written to tmp_path and scanned with
``analyze_paths``; role-scoped rules (HVD001/HVD003) opt in via the
``# hvdlint: role=`` marker instead of the built-in path lists, which is
exactly how any new module would.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.hvdlint import analyze_paths
from tools.hvdlint.engine import iter_python_files
from tools.hvdlint.rules import RULES

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a minimal config.py stand-in for HVD005 tests: exactly one aliased and
# one exact-name variable registered
FAKE_REGISTRY = textwrap.dedent("""\
    ENV_REGISTRY = (
        ("HOROVOD_CYCLE_TIME", True, "5.0", "common/config.py",
         "Cycle time."),
        ("HVD_COORDINATOR_ADDR", False, None, "mpi_ops.py",
         "Coordinator address."),
    )
""")


def lint_source(tmp_path, source, name="snippet.py", registry=None,
                baseline=None):
    """Write one fixture file and return its live + suppressed findings."""
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    reg = tmp_path / "fake_config.py"
    reg.write_text(registry if registry is not None else FAKE_REGISTRY)
    findings, _ = analyze_paths(
        [str(f)], baseline_path=baseline, env_registry_path=str(reg))
    return findings


def live(findings, rule=None):
    return [f for f in findings if not f.suppressed and
            (rule is None or f.rule == rule)]


# ---------------------------------------------------------------------------
# HVD001 — rank-divergent iteration
# ---------------------------------------------------------------------------

def test_hvd001_triggers_on_set_iteration_in_wire_module(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=wire
        pending = set()

        def plan():
            return [name for name in pending]
        """)
    assert [f.rule for f in live(found)] == ["HVD001"]


def test_hvd001_triggers_on_list_of_set_attribute(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=wire
        class Coord:
            def __init__(self):
                self._lost = set()

            def response(self):
                return list(self._lost)
        """)
    assert [f.rule for f in live(found)] == ["HVD001"]


def test_hvd001_sorted_and_dict_iteration_are_clean(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=wire
        pending = set()
        table = {}

        def plan():
            for name in sorted(pending):
                yield name
            for key in table:  # dicts are insertion-ordered: identical
                yield key      # across ranks by construction
        """)
    assert live(found) == []


def test_hvd001_ignores_non_wire_modules(tmp_path):
    found = lint_source(tmp_path, """\
        pending = set()

        def local_only():
            return [n for n in pending]
        """)
    assert live(found) == []


def test_hvd001_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=wire
        pending = set()

        def plan():
            # hvdlint: disable=HVD001(order feeds a local cache, never the wire)
            return [name for name in pending]
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD001"]


# ---------------------------------------------------------------------------
# HVD002 — lock order / self-deadlock
# ---------------------------------------------------------------------------

def test_hvd002_triggers_on_direct_reacquire(tmp_path):
    found = lint_source(tmp_path, """\
        import threading
        _lock = threading.Lock()

        def leaf():
            with _lock:
                with _lock:
                    return 1
        """)
    assert [f.rule for f in live(found)] == ["HVD002"]


def test_hvd002_triggers_on_call_graph_reacquire(tmp_path):
    # the metrics-registry reset() bug shape: hold the lock, call a
    # function whose body takes it again
    found = lint_source(tmp_path, """\
        import threading
        _lock = threading.Lock()

        def get_thing():
            with _lock:
                return 1

        def reset():
            with _lock:
                return get_thing()
        """)
    assert [f.rule for f in live(found)] == ["HVD002"]


def test_hvd002_triggers_on_inconsistent_order(tmp_path):
    found = lint_source(tmp_path, """\
        import threading
        a = threading.Lock()
        b = threading.Lock()

        def one():
            with a:
                with b:
                    pass

        def two():
            with b:
                with a:
                    pass
        """)
    assert any(f.rule == "HVD002" and "inconsistent" in f.message
               for f in live(found))


def test_hvd002_rlock_reentry_is_clean(tmp_path):
    found = lint_source(tmp_path, """\
        import threading
        _lock = threading.RLock()

        def outer():
            with _lock:
                return inner()

        def inner():
            with _lock:
                return 1
        """)
    assert live(found) == []


def test_hvd002_release_before_call_is_clean(tmp_path):
    # the fixed shape of reset(): the call happens after the with-region
    found = lint_source(tmp_path, """\
        import threading
        _lock = threading.Lock()

        def get_thing():
            with _lock:
                return 1

        def reset():
            with _lock:
                pass
            return get_thing()
        """)
    assert live(found) == []


# ---------------------------------------------------------------------------
# HVD003 — blocking call in the coordinator loop
# ---------------------------------------------------------------------------

def test_hvd003_triggers_on_unbounded_blocking(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=loop
        import socket
        import time

        def cycle(sock, thread):
            time.sleep(5)
            socket.create_connection(("peer", 1))
            thread.join()
        """)
    assert [f.rule for f in live(found)] == ["HVD003"] * 3


def test_hvd003_bounded_calls_are_clean(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=loop
        import socket
        import time

        def cycle(sock, thread, cycle_time_s):
            time.sleep(0.005)
            time.sleep(cycle_time_s)
            socket.create_connection(("peer", 1), timeout=2.0)
            thread.join(timeout=1.0)
        """)
    assert live(found) == []


def test_hvd003_ignores_modules_without_loop_role(tmp_path):
    found = lint_source(tmp_path, """\
        import time

        def launcher_wait():
            time.sleep(30)
        """)
    assert live(found) == []


# ---------------------------------------------------------------------------
# HVD004 — raw wall clock
# ---------------------------------------------------------------------------

def test_hvd004_triggers_on_time_time_and_from_import(tmp_path):
    found = lint_source(tmp_path, """\
        import time
        from time import time as now

        def stamp():
            return time.time(), time.time_ns(), now()
        """)
    assert [f.rule for f in live(found)] == ["HVD004"] * 3


def test_hvd004_monotonic_and_shared_clock_are_clean(tmp_path):
    found = lint_source(tmp_path, """\
        import time

        def stamp(clock):
            return time.monotonic(), time.perf_counter(), clock.ts_us()
        """)
    assert live(found) == []


def test_hvd004_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        import time

        def wall_stamp():
            return time.time()  # hvdlint: disable=HVD004(cross-process stamp)
        """)
    assert live(found) == []


# ---------------------------------------------------------------------------
# HVD005 — env-registry drift
# ---------------------------------------------------------------------------

def test_hvd005_triggers_on_unregistered_reads(tmp_path):
    found = lint_source(tmp_path, """\
        import os
        from horovod_tpu.common.config import env_int

        a = os.environ.get("HVD_NOT_REGISTERED")
        b = os.environ["HOROVOD_ALSO_MISSING"]
        c = "HVD_THIRD_ONE" in os.environ
        d = env_int("BRAND_NEW_KNOB", 3)
        """)
    hits = live(found, "HVD005")
    assert len(hits) == 4
    assert "HVD_NOT_REGISTERED" in hits[0].message


def test_hvd005_registered_reads_are_clean(tmp_path):
    found = lint_source(tmp_path, """\
        import os
        from horovod_tpu.common.config import env_float

        a = os.environ.get("HVD_COORDINATOR_ADDR")
        b = env_float("CYCLE_TIME", 5.0)   # aliased HOROVOD_/HVD_
        c = os.environ.get("HVD_CYCLE_TIME")  # the alias spelling
        d = os.environ.get("PATH")  # non-HVD names are out of scope
        """)
    assert live(found) == []


def test_hvd005_real_registry_parses_without_import(tmp_path):
    from tools.hvdlint import envdoc
    entries = envdoc.load_env_registry()
    names = {e["name"] for e in entries}
    assert "HOROVOD_FUSION_THRESHOLD" in names
    assert "HVD_COORDINATOR_ADDR" in names
    assert len(entries) >= 49
    lookup = envdoc.registry_lookup(entries)
    assert "HVD_FUSION_THRESHOLD" in lookup  # alias spelling


# ---------------------------------------------------------------------------
# HVD006 — swallowed exception
# ---------------------------------------------------------------------------

def test_hvd006_triggers_on_silent_broad_except(tmp_path):
    found = lint_source(tmp_path, """\
        def fetch(client):
            try:
                return client.cycle()
            except Exception:
                pass
        """)
    assert [f.rule for f in live(found)] == ["HVD006"]


def test_hvd006_narrow_logged_or_reraised_are_clean(tmp_path):
    found = lint_source(tmp_path, """\
        import logging
        log = logging.getLogger(__name__)

        def fetch(client):
            try:
                return client.cycle()
            except ConnectionError:
                return None

        def fetch2(client):
            try:
                return client.cycle()
            except Exception as exc:
                log.warning("cycle failed: %s", exc)
                return None

        def fetch3(client):
            try:
                return client.cycle()
            except Exception:
                raise
        """)
    assert live(found) == []


def test_hvd006_suppression_with_reason_honored(tmp_path):
    found = lint_source(tmp_path, """\
        def close(sock):
            try:
                sock.close()
            # hvdlint: disable=HVD006(teardown is best-effort)
            except Exception:
                pass
        """)
    assert live(found) == []


def test_reasonless_suppression_is_integrity_finding(tmp_path):
    found = lint_source(tmp_path, """\
        def close(sock):
            try:
                sock.close()
            except Exception:  # hvdlint: disable=HVD006
                pass
        """)
    rules = sorted(f.rule for f in live(found))
    # the disable does NOT suppress, and is itself reported
    assert rules == ["HVD000", "HVD006"]


# ---------------------------------------------------------------------------
# HVD007 — jit purity
# ---------------------------------------------------------------------------

def test_hvd007_triggers_on_side_effects_in_traced_fn(tmp_path):
    found = lint_source(tmp_path, """\
        import functools
        import os
        import time
        import jax

        @jax.jit
        def step(x):
            print("tracing")
            return x * time.time()

        @functools.partial(jax.jit, static_argnums=1)
        def step2(x, n):
            return x * float(os.environ.get("HVD_COORDINATOR_ADDR", 1))
        """)
    # (the raw time.time() also trips HVD004 — that rule is file-wide)
    assert [f.rule for f in live(found, "HVD007")] == ["HVD007"] * 3


def test_hvd007_pure_traced_and_impure_untraced_are_clean(tmp_path):
    found = lint_source(tmp_path, """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.sum(x * 2.0)

        def host_side():
            print("not traced, print away")
        """)
    assert live(found) == []


def test_hvd007_catches_lambda_passed_to_jit(tmp_path):
    found = lint_source(tmp_path, """\
        import jax

        _replicate = jax.jit(lambda x: print(x) or x)
        """)
    assert [f.rule for f in live(found)] == ["HVD007"]


# ---------------------------------------------------------------------------
# HVD008 — span leak
# ---------------------------------------------------------------------------

def test_hvd008_triggers_on_discarded_span(tmp_path):
    found = lint_source(tmp_path, """\
        from horovod_tpu.utils import tracing as hvd_tracing

        def enqueue(tracer, name):
            tracer.span("negotiate", tensor=name)
        """)
    assert [f.rule for f in live(found)] == ["HVD008"]


def test_hvd008_triggers_on_discarded_annotate_chain(tmp_path):
    # annotate() returns the span, so chaining doesn't close it
    found = lint_source(tmp_path, """\
        def enqueue(name):
            from horovod_tpu.utils.tracing import get_tracer
            get_tracer().span("enqueue", tensor=name).annotate(op="sum")
        """)
    assert [f.rule for f in live(found)] == ["HVD008"]


def test_hvd008_triggers_on_assigned_never_closed(tmp_path):
    found = lint_source(tmp_path, """\
        def run(tracer):
            s = tracer.span("execute")
            do_work()
        """)
    hits = live(found, "HVD008")
    assert len(hits) == 1 and "'s'" in hits[0].message


def test_hvd008_clean_forms(tmp_path):
    found = lint_source(tmp_path, """\
        def lexical(tracer):
            with tracer.span("fusion") as fspan:
                fspan.annotate(n_buckets=3)

        def explicit(tracer):
            s = tracer.span("execute")
            try:
                do_work()
                s.close(bytes=128)
            except Exception as exc:
                s.abort(exc)
                raise

        def stored(tracer, entry):
            # ownership handed to the entry: closed elsewhere by design
            entry.span = tracer.span("negotiate")

        def escapes(tracer):
            a = tracer.span("step")
            register(a)           # passed on: callee owns the close
            b = tracer.span("cycle")
            return b              # returned: caller owns the close
        """)
    assert live(found) == []


def test_hvd008_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        def fire_and_forget(tracer):
            tracer.span("enqueue")  # hvdlint: disable=HVD008(leak drill)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD008"]


# ---------------------------------------------------------------------------
# HVD009 — ad-hoc numerics probe
# ---------------------------------------------------------------------------

def test_hvd009_triggers_on_adhoc_isnan(tmp_path):
    found = lint_source(tmp_path, """\
        import jax.numpy as jnp

        def flush(grad):
            if jnp.isnan(grad).any():
                raise ValueError("nan gradient")
            return grad
        """)
    assert [f.rule for f in live(found)] == ["HVD009"]
    assert "isnan" in live(found)[0].message


def test_hvd009_triggers_on_bare_imported_name(tmp_path):
    found = lint_source(tmp_path, """\
        from numpy import isfinite

        def guard(x):
            return isfinite(x).all()
        """)
    assert [f.rule for f in live(found)] == ["HVD009"]


def test_hvd009_sanctioned_numerics_module_is_clean(tmp_path):
    mod = tmp_path / "horovod_tpu" / "utils"
    mod.mkdir(parents=True)
    f = mod / "numerics.py"
    f.write_text(textwrap.dedent("""\
        import jax.numpy as jnp

        def tensor_stats(x):
            return jnp.isfinite(x)
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert live(findings) == []


def test_hvd009_routed_stats_call_is_clean(tmp_path):
    found = lint_source(tmp_path, """\
        from horovod_tpu.utils import numerics

        def flush(flat, sizes):
            return numerics.segment_stats(flat, sizes)
        """)
    assert live(found) == []


def test_hvd009_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        import math

        def host_guard(x):
            return math.isnan(x)  # hvdlint: disable=HVD009(host scalar)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD009"]


# ---------------------------------------------------------------------------
# HVD010 — wire-dtype cast bypasses the codec registry
# ---------------------------------------------------------------------------

def test_hvd010_triggers_on_direct_int8_cast(tmp_path):
    found = lint_source(tmp_path, """\
        import jax.numpy as jnp

        def narrow(grad):
            return grad.astype(jnp.int8)
        """)
    assert [f.rule for f in live(found)] == ["HVD010"]
    assert "int8" in live(found)[0].message


def test_hvd010_triggers_on_string_and_npdtype_forms(tmp_path):
    found = lint_source(tmp_path, """\
        import numpy as np

        def narrow(grad, other):
            a = grad.astype("float8_e4m3fn")
            b = other.astype(np.dtype("uint8"))
            return a, b
        """)
    assert sorted(f.rule for f in live(found)) == ["HVD010", "HVD010"]


def test_hvd010_wide_casts_are_clean(tmp_path):
    found = lint_source(tmp_path, """\
        import jax.numpy as jnp

        def widen(grad):
            # bf16/f32 casts are numerics policy, not wire format
            return grad.astype(jnp.bfloat16).astype(jnp.float32)
        """)
    assert live(found) == []


def test_hvd010_sanctioned_quantization_module_is_clean(tmp_path):
    mod = tmp_path / "horovod_tpu" / "ops"
    mod.mkdir(parents=True)
    f = mod / "quantization.py"
    f.write_text(textwrap.dedent("""\
        import jax.numpy as jnp

        def encode(x):
            return x.astype(jnp.int8)
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert live(findings) == []


def test_hvd010_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        import jax.numpy as jnp

        def tokens(ids):
            return ids.astype(jnp.uint8)  # hvdlint: disable=HVD010(token bytes, not a wire codec)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD010"]


# ---------------------------------------------------------------------------
# HVD011 — blocking host sync in the serving decode loop
# ---------------------------------------------------------------------------

def test_hvd011_triggers_on_host_syncs_in_serve_loop(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_loop
        import jax
        import numpy as np

        def decode_step_host(x):
            tok = jax.device_get(x)
            x.block_until_ready()
            return np.asarray(tok)
        """)
    assert [f.rule for f in live(found)] == ["HVD011"] * 3


def test_hvd011_triggers_in_real_serving_path(tmp_path):
    mod = tmp_path / "horovod_tpu" / "serving"
    mod.mkdir(parents=True)
    f = mod / "engine.py"
    f.write_text(textwrap.dedent("""\
        import jax

        def peek(x):
            return jax.device_get(x)
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert [f.rule for f in live(findings)] == ["HVD011"]


def test_hvd011_jnp_asarray_and_outside_scope_are_clean(tmp_path):
    # jnp.asarray is host->device: legal inside the loop
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_loop
        import jax.numpy as jnp

        def feed(tokens):
            return jnp.asarray(tokens)
        """)
    assert live(found) == []
    # and without the role/path scope, host syncs are someone else's
    # business (training scripts readback all the time)
    found = lint_source(tmp_path, """\
        import jax

        def fetch(x):
            return jax.device_get(x)
        """)
    assert live(found) == []


def test_hvd011_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_loop
        import jax
        import numpy as np

        def sample(nxt):
            # hvdlint: disable=HVD011(the per-step batched token readback)
            return np.asarray(jax.device_get(nxt))
        """)
    assert live(found) == []
    assert sorted(f.rule for f in found if f.suppressed == "inline") == \
        ["HVD011", "HVD011"]


# ---------------------------------------------------------------------------
# HVD012 — ad-hoc training-state serialization
# ---------------------------------------------------------------------------

def test_hvd012_triggers_on_numpy_and_torch_dumps(tmp_path):
    found = lint_source(tmp_path, """\
        import numpy as np
        import torch

        def dump(path, params, model):
            np.savez(path, **params)
            np.savez_compressed(path + ".z", **params)
            np.save(path + ".npy", params["w"])
            torch.save(model.state_dict(), path + ".pt")
        """)
    assert [f.rule for f in live(found)] == ["HVD012"] * 4


def test_hvd012_sanctioned_checkpoint_module_is_clean(tmp_path):
    mod = tmp_path / "horovod_tpu" / "utils"
    mod.mkdir(parents=True)
    f = mod / "checkpoint.py"
    f.write_text(textwrap.dedent("""\
        import numpy as np

        def write_shard(path, arrays):
            np.savez(path, **arrays)
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert live(findings) == []


def test_hvd012_non_dump_writes_are_clean(tmp_path):
    # json/pickle scratch and this repo's own checkpoint entry points
    # are not array dumps; np.save needs the np receiver to count
    found = lint_source(tmp_path, """\
        import json
        import pickle
        from horovod_tpu.utils import checkpoint

        def scratch(path, obj, tree):
            json.dump(obj, open(path, "w"))
            pickle.dumps(obj)
            checkpoint.save(path, tree)

        def save(path, obj):
            return path, obj
        """)
    assert live(found) == []


def test_hvd012_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        import numpy as np

        def export_onnx_weights(path, arrays):
            # hvdlint: disable=HVD012(interchange export, not durable training state)
            np.savez(path, **arrays)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD012"]


# ---------------------------------------------------------------------------
# HVD013 — ad-hoc step timers in hot-path modules
# ---------------------------------------------------------------------------

def test_hvd013_triggers_on_perf_counter_in_hot_path(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path
        import time

        def step(fn, x):
            t0 = time.perf_counter()
            y = fn(x)
            dt = time.perf_counter_ns() - t0
            return y, dt
        """)
    assert [f.rule for f in live(found)] == ["HVD013"] * 2


def test_hvd013_triggers_on_from_import_alias(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path
        from time import perf_counter as pc

        def step(fn, x):
            t0 = pc()
            return fn(x), pc() - t0
        """)
    assert [f.rule for f in live(found)] == ["HVD013"] * 2


def test_hvd013_triggers_in_real_ops_path(tmp_path):
    mod = tmp_path / "horovod_tpu" / "ops"
    mod.mkdir(parents=True)
    f = mod / "fusion.py"
    f.write_text(textwrap.dedent("""\
        import time

        def flush(buckets):
            t0 = time.perf_counter()
            return buckets, t0
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert [f.rule for f in live(findings)] == ["HVD013"]


def test_hvd013_monotonic_refs_and_cold_paths_are_clean(tmp_path):
    # time.monotonic is the shared clock's base and the wire-timeout
    # primitive; a bare attribute reference (clock=time.monotonic) is
    # not a timing read; and outside the hot-path scope raw timers are
    # someone else's business
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path
        import time

        def deadline(timeout_s):
            return time.monotonic() + timeout_s

        def make_engine():
            return dict(clock=time.monotonic, now=time.perf_counter)
        """)
    assert live(found) == []
    found = lint_source(tmp_path, """\
        import time

        def bench_once(fn):
            t0 = time.perf_counter()
            fn()
            return time.perf_counter() - t0
        """)
    assert live(found) == []


def test_hvd013_instrument_step_is_sanctioned(tmp_path):
    mod = tmp_path / "horovod_tpu"
    mod.mkdir(parents=True)
    f = mod / "trainer.py"
    f.write_text(textwrap.dedent("""\
        import time

        def instrument_step(step_fn):
            def wrapped(*a):
                t0 = time.perf_counter()
                out = step_fn(*a)
                return out, time.perf_counter() - t0
            return wrapped
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert live(findings) == []


def test_hvd013_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path
        import time

        def flush(buckets):
            # hvdlint: disable=HVD013(flush duration feeding the hvd_fusion_flush_seconds histogram)
            t0 = time.perf_counter()
            return buckets, t0
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD013"]


def test_hvd014_triggers_on_request_ts_delta(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_path

        def retire(now, req, st):
            ttft = now - req.arrival_ts
            gap = now - st.last_token_ts
            return ttft, gap
        """)
    assert [f.rule for f in live(found)] == ["HVD014"] * 2


def test_hvd014_triggers_in_real_serving_path(tmp_path):
    mod = tmp_path / "horovod_tpu" / "serving"
    mod.mkdir(parents=True)
    f = mod / "engine.py"
    f.write_text(textwrap.dedent("""\
        def deadline_left(now, req):
            return req.deadline_s - (now - req.arrival_ts)
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert [f.rule for f in live(findings)] == ["HVD014"]


def test_hvd014_trace_layer_is_sanctioned(tmp_path):
    # serving/tracing.py IS the request-timing layer: the same delta
    # there is the instrument, not a rival
    mod = tmp_path / "horovod_tpu" / "serving"
    mod.mkdir(parents=True)
    f = mod / "tracing.py"
    f.write_text(textwrap.dedent("""\
        def waited(now, req):
            return now - req.arrival_ts
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert live(findings) == []


def test_hvd014_non_ts_deltas_and_outside_scope_clean(tmp_path):
    # subtraction per se is fine — only request-lifecycle timestamp
    # attributes mark a latency measurement
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_path

        def trim(req, budget):
            return len(req.prompt) - budget

        def room(ledger):
            return ledger.capacity - ledger.used
        """)
    assert live(found) == []
    # outside the serving plane the same delta is someone else's
    # business (bench harnesses, tests)
    found = lint_source(tmp_path, """\
        def waited(now, req):
            return now - req.arrival_ts
        """)
    assert live(found) == []


def test_hvd014_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_path

        def observe_ttft(hist, now, req):
            # hvdlint: disable=HVD014(TTFT histogram on the shared registry consumes this delta)
            hist.observe(now - req.arrival_ts)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD014"]


# ---------------------------------------------------------------------------
# HVD015 — ad-hoc weight load in the serving plane
# ---------------------------------------------------------------------------

def test_hvd015_triggers_on_manager_restore_in_serve_path(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_path

        def refresh(self, step):
            params = self.manager.restore(step)
            extra = self.checkpoint.restore_with_extra(like=params)
            return params, extra
        """)
    assert [f.rule for f in live(found)] == ["HVD015"] * 2


def test_hvd015_triggers_in_real_serving_module(tmp_path):
    mod = tmp_path / "horovod_tpu" / "serving"
    mod.mkdir(parents=True)
    f = mod / "engine.py"
    f.write_text(textwrap.dedent("""\
        import numpy as np

        def reload_weights(path):
            return np.load(path)
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert [f.rule for f in live(findings)] == ["HVD015"]


def test_hvd015_triggers_on_bare_import_alias(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_path
        from horovod_tpu.utils.checkpoint import restore

        def refresh(path, like):
            return restore(path, like=like)
        """)
    assert [f.rule for f in live(found)] == ["HVD015"]


def test_hvd015_subscriber_layer_is_sanctioned(tmp_path):
    # fleet/subscriber.py IS the weight-load path: restore there is the
    # mechanism, not a rival
    mod = tmp_path / "horovod_tpu" / "fleet"
    mod.mkdir(parents=True)
    f = mod / "subscriber.py"
    f.write_text(textwrap.dedent("""\
        from horovod_tpu.utils import checkpoint

        def _restore(d, like):
            return checkpoint.restore_with_extra(d, like=like)
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert live(findings) == []


def test_hvd015_outside_serving_plane_is_clean(tmp_path):
    # the trainer restoring its own checkpoint is the normal resume
    # path, not an ad-hoc serving-side load
    found = lint_source(tmp_path, """\
        def resume(self):
            return self.manager.restore(like=self.params)
        """)
    assert live(found) == []


def test_hvd015_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=serve_path

        def warm_start(self):
            # hvdlint: disable=HVD015(one-time boot load before the subscriber exists)
            return self.manager.restore(like=self.params)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD015"]


# ---------------------------------------------------------------------------
# HVD016 — full-tree barrier in the backward→apply window
# ---------------------------------------------------------------------------

def test_hvd016_triggers_on_synchronize_comprehension(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path

        def reduce_all(mpi_ops, handles):
            return [mpi_ops.synchronize(h) for h in handles]
        """)
    assert [f.rule for f in live(found)] == ["HVD016"]


def test_hvd016_triggers_on_block_until_ready(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path
        import jax

        def step(backward, apply, x):
            grads = backward(x)
            jax.block_until_ready(grads)
            return apply(grads)
        """)
    assert [f.rule for f in live(found)] == ["HVD016"]


def test_hvd016_triggers_in_real_optim_path(tmp_path):
    mod = tmp_path / "horovod_tpu"
    mod.mkdir(parents=True)
    f = mod / "optim.py"
    f.write_text(textwrap.dedent("""\
        def drain(mpi_ops, handles):
            return [mpi_ops.synchronize(h) for h in handles]
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert [f.rule for f in live(findings)] == ["HVD016"]


def test_hvd016_instrument_step_sync_is_sanctioned(tmp_path):
    # the measurement boundary: instrument_step's own block_until_ready
    # IS the step wall's definition, not a rival barrier
    mod = tmp_path / "horovod_tpu"
    mod.mkdir(parents=True)
    f = mod / "trainer.py"
    f.write_text(textwrap.dedent("""\
        import jax

        def instrument_step(step_fn):
            def wrapped(*a):
                out = step_fn(*a)
                jax.block_until_ready(out)
                return out
            return wrapped
        """))
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert live(findings) == []


def test_hvd016_per_bucket_sync_and_cold_paths_are_clean(tmp_path):
    # a single synchronize as results are consumed is the overlap
    # plane's OWN idiom; and outside the hot-path scope the barrier is
    # someone else's call
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path

        def consume(mpi_ops, handle, apply):
            return apply(mpi_ops.synchronize(handle))
        """)
    assert live(found) == []
    found = lint_source(tmp_path, """\
        import jax

        def eval_once(model, x):
            out = model(x)
            jax.block_until_ready(out)
            return [sync(h) for h in out]
        """)
    assert live(found) == []


def test_hvd016_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=hot_path

        def drain(mpi_ops, handles):
            # hvdlint: disable=HVD016(checkpoint boundary: every shard must be on host before save)
            return [mpi_ops.synchronize(h) for h in handles]
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD016"]


# ---------------------------------------------------------------------------
# HVD017 — direct engine admission outside the router front door
# ---------------------------------------------------------------------------

def test_hvd017_triggers_on_engine_submit_and_admission_queue(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=client_path
        from horovod_tpu.serving import AdmissionQueue

        def drive(engine, requests):
            queue = AdmissionQueue(max_depth=8)
            for req in requests:
                engine.submit(req)
        """)
    assert [f.rule for f in live(found)] == ["HVD017"] * 2


def test_hvd017_scopes_to_client_dirs(tmp_path):
    # same code under examples/ fires without any role marker...
    mod = tmp_path / "examples"
    mod.mkdir(parents=True)
    f = mod / "demo.py"
    f.write_text("def go(engine, req):\n    engine.submit(req)\n")
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert [f.rule for f in live(findings)] == ["HVD017"]
    # ...and the identical snippet with no role and no client dir is
    # out of scope (the engine's own internals are the implementation)
    found = lint_source(tmp_path, """\
        def go(engine, req):
            engine.submit(req)
        """)
    assert live(found) == []


def test_hvd017_router_submit_is_sanctioned(tmp_path):
    # Router.submit IS the front door; queue.submit inside the serving
    # plane is somebody else's receiver
    found = lint_source(tmp_path, """\
        # hvdlint: role=client_path

        def drive(router, queue, requests):
            for req in requests:
                router.submit(req)
            queue.submit(requests[0])
        """)
    assert live(found) == []


def test_hvd017_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=client_path

        def bench_arm(engine, req):
            # hvdlint: disable=HVD017(single-replica bench arm: the bare engine is the thing measured)
            engine.submit(req)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD017"]


# ---------------------------------------------------------------------------
# HVD018 — unbounded retry loop
# ---------------------------------------------------------------------------

def test_hvd018_triggers_on_deadline_free_sleep_loop(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=retry_path
        import time

        def wait_for_pointer(path):
            while True:
                if path.exists():
                    return path.read_text()
                time.sleep(0.1)
        """)
    assert [f.rule for f in live(found)] == ["HVD018"]


def test_hvd018_deadline_check_bounds_the_loop(tmp_path):
    # the run/mpi.py rendezvous shape: monotonic-vs-deadline compare
    # anywhere in the body is the bound this rule wants
    found = lint_source(tmp_path, """\
        # hvdlint: role=retry_path
        import time

        def wait_for_pointer(path, timeout_s):
            deadline = time.monotonic() + timeout_s
            while True:
                if path.exists():
                    return path.read_text()
                if time.monotonic() > deadline:
                    raise TimeoutError(path)
                time.sleep(0.1)
        """)
    assert live(found) == []


def test_hvd018_bound_named_operand_counts(tmp_path):
    # a compare against a timeout/deadline-named value also reads as a
    # bound even when the clock call is hoisted out of the compare
    found = lint_source(tmp_path, """\
        # hvdlint: role=retry_path
        import time

        def poll(conn, timeout_s):
            waited = 0.0
            while True:
                if conn.ready():
                    return conn.take()
                if waited >= timeout_s:
                    raise TimeoutError
                time.sleep(0.05)
                waited += 0.05
        """)
    assert live(found) == []


def test_hvd018_sleepless_drain_loop_not_flagged(tmp_path):
    # a blocking-recv drain loop is bounded by its peer's EOF — no
    # sleep, no finding (the serving queue's pop loop is this shape)
    found = lint_source(tmp_path, """\
        # hvdlint: role=retry_path

        def drain(sock):
            while True:
                msg = sock.recv()
                if not msg:
                    break
        """)
    assert live(found) == []


def test_hvd018_scopes_to_control_planes(tmp_path):
    # identical snippet with no role marker and no scoped dir is out
    # of scope
    found = lint_source(tmp_path, """\
        import time

        def wait(path):
            while True:
                time.sleep(0.1)
        """)
    assert live(found) == []
    # ...and under horovod_tpu/router/ it fires without a marker
    mod = tmp_path / "horovod_tpu" / "router"
    mod.mkdir(parents=True)
    f = mod / "spin.py"
    f.write_text("import time\n\ndef wait(path):\n"
                 "    while True:\n        time.sleep(0.1)\n")
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    findings, _ = analyze_paths([str(f)], env_registry_path=str(reg))
    assert [f.rule for f in live(findings)] == ["HVD018"]


def test_hvd018_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=retry_path
        import time

        def serve(sock):
            # hvdlint: disable=HVD018(bounded by peer EOF; the sleep is an injected chaos fault)
            while True:
                req = sock.recv()
                time.sleep(req.delay_s)
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD018"]


# ---------------------------------------------------------------------------
# HVD019 — ad-hoc sharding outside the mesh plane
# ---------------------------------------------------------------------------

def test_hvd019_triggers_on_bare_namedsharding(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=mesh_path
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(x, mesh):
            return jax.device_put(x, NamedSharding(mesh, P("dp")))
        """)
    assert [f.rule for f in live(found)] == ["HVD019"]


def test_hvd019_triggers_on_device_put_with_inline_mesh(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=mesh_path
        import jax
        from jax.sharding import Mesh

        def place(x, devices):
            return jax.device_put(x, Mesh(devices, ("dp",)))
        """)
    assert [f.rule for f in live(found)] == ["HVD019"]


def test_hvd019_sees_through_import_aliases(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=mesh_path
        from jax.sharding import NamedSharding as NS

        def place(x, mesh, spec):
            return NS(mesh, spec)
        """)
    assert [f.rule for f in live(found)] == ["HVD019"]


def test_hvd019_mesh_lib_helpers_are_sanctioned(tmp_path):
    # the fix the rule points at: specs routed through parallel/mesh.py
    found = lint_source(tmp_path, """\
        # hvdlint: role=mesh_path
        from horovod_tpu.parallel import mesh as mesh_lib
        from jax.sharding import PartitionSpec as P

        def place(tree, spec_tree, mesh):
            s = mesh_lib.named_sharding(P("dp"), mesh)
            return mesh_lib.device_put_tree(tree, spec_tree, mesh)
        """)
    assert live(found, "HVD019") == []


def test_hvd019_scoped_to_data_plane_modules(tmp_path):
    # no role marker, not under trainer/serving/ops: out of scope
    found = lint_source(tmp_path, """\
        from jax.sharding import NamedSharding

        def place(x, mesh, spec):
            return NamedSharding(mesh, spec)
        """)
    assert live(found, "HVD019") == []


def test_hvd019_fires_under_serving_without_marker_but_not_in_mesh_py(
        tmp_path):
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    src = ("from jax.sharding import NamedSharding\n\n"
           "def place(x, mesh, spec):\n"
           "    return NamedSharding(mesh, spec)\n")
    serve = tmp_path / "horovod_tpu" / "serving"
    serve.mkdir(parents=True)
    (serve / "warm.py").write_text(src)
    plane = tmp_path / "horovod_tpu" / "parallel"
    plane.mkdir(parents=True)
    (plane / "mesh.py").write_text(src)
    findings, _ = analyze_paths(
        [str(serve / "warm.py"), str(plane / "mesh.py")],
        env_registry_path=str(reg))
    assert [(f.rule, "serving" in f.file) for f in live(findings)] == \
        [("HVD019", True)]


def test_hvd019_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=mesh_path
        from jax.sharding import NamedSharding, PartitionSpec as P

        def rendezvous_sharding(mesh):
            # hvdlint: disable=HVD019(per-process rendezvous mesh, not the data plane)
            return NamedSharding(mesh, P("proc"))
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD019"]


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------

def test_baseline_consumes_match_and_requires_reason(tmp_path):
    src = """\
        import time

        def stamp():
            return time.time()
        """
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "file": str(tmp_path / "snippet.py"), "rule": "HVD004",
        "match": "return time.time()", "count": 1,
        "reason": "wall stamp compared across processes"}]}))
    found = lint_source(tmp_path, src, baseline=str(bl))
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "baseline"] == \
        ["HVD004"]

    # an empty reason turns the entry itself into a finding
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "file": str(tmp_path / "snippet.py"), "rule": "HVD004",
        "match": "return time.time()", "count": 1, "reason": ""}]}))
    found = lint_source(tmp_path, src, baseline=str(bl))
    assert sorted(f.rule for f in live(found)) == ["HVD000"]


def test_stale_baseline_entry_is_reported(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "file": str(tmp_path / "snippet.py"), "rule": "HVD004",
        "match": "return time.time()", "count": 1,
        "reason": "was a wall stamp"}]}))
    found = lint_source(tmp_path, "x = 1\n", baseline=str(bl))
    hits = live(found, "HVD000")
    assert len(hits) == 1 and "stale" in hits[0].message


def test_syntax_error_is_integrity_finding(tmp_path):
    found = lint_source(tmp_path, "def broken(:\n")
    assert [f.rule for f in live(found)] == ["HVD000"]


def test_walk_excludes_pycache_and_native(tmp_path):
    (tmp_path / "__pycache__").mkdir()
    (tmp_path / "__pycache__" / "junk.py").write_text("x = 1\n")
    (tmp_path / "_native").mkdir()
    (tmp_path / "_native" / "gen.py").write_text("x = 1\n")
    (tmp_path / "real.py").write_text("x = 1\n")
    files = iter_python_files([str(tmp_path)])
    assert [os.path.basename(f) for f in files] == ["real.py"]


# ---------------------------------------------------------------------------
# HVD020 — ad-hoc memory probe outside the memory plane
# ---------------------------------------------------------------------------

def test_hvd020_triggers_on_device_memory_stats(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=mem_path
        import jax

        def headroom():
            return jax.devices()[0].memory_stats()
        """)
    assert [f.rule for f in live(found)] == ["HVD020"]


def test_hvd020_triggers_on_live_arrays_and_memory_analysis(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=mem_path
        import jax

        def audit(compiled):
            n = sum(a.nbytes for a in jax.live_arrays())
            return n, compiled.memory_analysis()
        """)
    assert [f.rule for f in live(found)] == ["HVD020", "HVD020"]


def test_hvd020_memory_plane_wrappers_are_sanctioned(tmp_path):
    # the fix the rule points at: probes routed through utils/memory.py
    found = lint_source(tmp_path, """\
        # hvdlint: role=mem_path
        from horovod_tpu.utils import memory as hvd_memory

        def headroom():
            hvd_memory.get_ledger().account_tree("params", {})
            return hvd_memory.step_peak_bytes()
        """)
    assert live(found, "HVD020") == []


def test_hvd020_scoped_to_trainer_serving_ops(tmp_path):
    # no role marker, not under trainer/serving/ops: out of scope
    found = lint_source(tmp_path, """\
        import jax

        def headroom():
            return jax.devices()[0].memory_stats()
        """)
    assert live(found, "HVD020") == []


def test_hvd020_fires_under_serving_but_not_in_memory_py(tmp_path):
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    src = ("import jax\n\n"
           "def headroom():\n"
           "    return jax.devices()[0].memory_stats()\n")
    serve = tmp_path / "horovod_tpu" / "serving"
    serve.mkdir(parents=True)
    (serve / "probe.py").write_text(src)
    plane = tmp_path / "horovod_tpu" / "utils"
    plane.mkdir(parents=True)
    (plane / "memory.py").write_text(src)
    findings, _ = analyze_paths(
        [str(serve / "probe.py"), str(plane / "memory.py")],
        env_registry_path=str(reg))
    assert [(f.rule, "serving" in f.file) for f in live(findings)] == \
        [("HVD020", True)]


def test_hvd020_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=mem_path
        import jax

        def debug_dump():
            # hvdlint: disable=HVD020(one-shot debug CLI, not a run path)
            return jax.devices()[0].memory_stats()
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD020"]


# ---------------------------------------------------------------------------
# HVD023 — ad-hoc alert outside the alerting plane
# ---------------------------------------------------------------------------

def test_hvd023_triggers_on_quantile_threshold_with_warning(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=alert_path
        import logging
        from horovod_tpu.utils import metrics as hvd_metrics

        log = logging.getLogger(__name__)

        def watch(bounds, counts, slo):
            p99 = hvd_metrics.histogram_quantile(bounds, counts, 0.99)
            if p99 > slo:
                log.warning("ttft p99 %s over slo %s", p99, slo)
        """)
    assert [f.rule for f in live(found)] == ["HVD023"]


def test_hvd023_triggers_on_burn_rate_with_event_and_dump(tmp_path):
    # the full private ladder: burn-rate compare -> event + flight dump
    found = lint_source(tmp_path, """\
        # hvdlint: role=alert_path
        from horovod_tpu.utils import metrics, tracing

        def police(good, bad, target):
            burn_rate = (bad / max(good + bad, 1)) / (1 - target)
            if burn_rate > 4.0:
                metrics.get_registry().event("goodput_burn", burn=burn_rate)
                tracing.dump_on_failure("goodput_burn")
        """)
    assert [f.rule for f in live(found)] == ["HVD023"]


def test_hvd023_compare_without_escalation_is_control_not_alert(tmp_path):
    # thresholding a p99 to *actuate* (no warn/event/dump) is a control
    # decision — the elastic/canary controllers' shape — not an alert
    found = lint_source(tmp_path, """\
        # hvdlint: role=alert_path
        def decide(win, slo):
            ttft_p99 = win.ttft_p99()
            if ttft_p99 > slo:
                return "scale_up"
            return "hold"
        """)
    assert live(found, "HVD023") == []


def test_hvd023_escalation_without_slo_signal_not_flagged(tmp_path):
    # warning on a plain state flag is the storm-ladder shape: no
    # SLO-shaped read in the test, so no finding
    found = lint_source(tmp_path, """\
        # hvdlint: role=alert_path
        import logging

        log = logging.getLogger(__name__)

        def escalate(storming, misses):
            if storming and misses > 4:
                log.warning("recompile storm: %d misses", misses)
        """)
    assert live(found, "HVD023") == []


def test_hvd023_fires_under_router_but_not_in_alerts_py(tmp_path):
    reg = tmp_path / "fake_config.py"
    reg.write_text(FAKE_REGISTRY)
    src = ("import logging\n"
           "log = logging.getLogger(__name__)\n\n"
           "def watch(win, slo):\n"
           "    ttft_p99 = win.p99()\n"
           "    if ttft_p99 > slo:\n"
           "        log.warning('over slo')\n")
    router = tmp_path / "horovod_tpu" / "router"
    router.mkdir(parents=True)
    (router / "watchdog.py").write_text(src)
    plane = tmp_path / "horovod_tpu" / "utils"
    plane.mkdir(parents=True)
    (plane / "alerts.py").write_text(src)
    findings, _ = analyze_paths(
        [str(router / "watchdog.py"), str(plane / "alerts.py")],
        env_registry_path=str(reg))
    assert [(f.rule, "router" in f.file) for f in live(findings)] == \
        [("HVD023", True)]


def test_hvd023_out_of_scope_without_role(tmp_path):
    found = lint_source(tmp_path, """\
        import logging

        log = logging.getLogger(__name__)

        def watch(p99, slo):
            if p99 > slo:
                log.warning("over slo")
        """)
    assert live(found, "HVD023") == []


def test_hvd023_suppression_honored(tmp_path):
    found = lint_source(tmp_path, """\
        # hvdlint: role=alert_path
        import logging

        log = logging.getLogger(__name__)

        def grade(after_p99, baseline_p99, x):
            # hvdlint: disable=HVD023(in-plane grading actuates a rollback; the alerting plane watches hvd_route_breaker_trips_total)
            if after_p99 > x * baseline_p99:
                log.warning("graded change breached; rolling back")
        """)
    assert live(found) == []
    assert [f.rule for f in found if f.suppressed == "inline"] == \
        ["HVD023"]


# ---------------------------------------------------------------------------
# rule catalog + CLI + end-to-end gate
# ---------------------------------------------------------------------------

def test_every_rule_has_catalog_entry():
    assert sorted(RULES) == \
        [f"HVD{i:03d}" for i in range(1, 21)] + ["HVD023"]
    for rule in RULES.values():
        assert rule.summary
        assert len(rule.explain) > 200  # the full story, not a stub


def test_cli_explain_and_json(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint", "--explain", "HVD002"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert out.returncode == 0
    assert "reset()" in out.stdout

    snippet = tmp_path / "s.py"
    snippet.write_text("import time\nt = time.time()\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint", str(snippet),
         "--format", "json", "--baseline", "none"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert out.returncode == 1
    payload = json.loads(out.stdout)
    assert payload["live"] == 1
    assert payload["findings"][0]["rule"] == "HVD004"


@pytest.mark.slow
def test_repo_lints_clean_end_to_end():
    """The CI gate itself: zero unbaselined findings over the repo."""
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint",
         "horovod_tpu", "tools", "bench.py", "examples"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert out.returncode == 0, out.stdout + out.stderr


@pytest.mark.slow
def test_envdoc_matches_registry_end_to_end():
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint", "--check-envdoc"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
