"""Memory & compile observability plane (docs/memory.md,
utils/memory.py): HBM-ledger attribution against hand-computed bytes,
the pre-flight planner validated against the measured ledger on real
placed state (dp-only and dp×tp=2), the recompile-storm escalation
ladder (event → warning → deduped flight dump), the GSPMD resharding
sentinel (mis-specced drill + the clean make_gspmd_step negative arm),
and the flight-dump/postmortem surfacing. Runs on the conftest
8-device virtual CPU mesh; no coordinator."""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import trainer
from horovod_tpu.models import transformer as tr
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.utils import memory as hvd_memory
from horovod_tpu.utils import metrics as hvd_metrics
from horovod_tpu.utils import tracing as hvd_tracing

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import hvd_postmortem  # noqa: E402

# the planner's accuracy contract (docs/memory.md §2, ISSUE 18)
PLAN_RTOL = 0.15


@pytest.fixture(autouse=True)
def _fresh_memory_plane():
    """Every test starts with the plane force-enabled and fresh
    singletons, and ends back at the env default — ledger/tracker
    leakage between tests is exactly what reset() exists to prevent."""
    hvd_memory.reset(enabled=True)
    mesh_lib.reset_global_mesh()
    yield
    mesh_lib.reset_global_mesh()
    hvd_memory.reset()


@pytest.fixture
def reg():
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()


def _values(snap, name):
    return {tuple(sorted(v["labels"].items())): v["value"]
            for v in snap["metrics"].get(name, {}).get("values", [])}


# ---------------------------------------------------------------------------
# the HBM ledger: attribution vs hand-computed bytes
# ---------------------------------------------------------------------------

class TestLedger:
    def test_account_tree_matches_hand_computed(self):
        ledger = hvd_memory.HBMLedger(capacity_bytes=1 << 20)
        params = {"w": jnp.zeros((16, 32), jnp.float32),
                  "b": jnp.zeros((32,), jnp.float32)}
        ledger.account_tree("params", params)
        want = 16 * 32 * 4 + 32 * 4
        snap = ledger.snapshot()
        assert snap["components"]["params"] == want
        assert snap["total_bytes"] == want
        assert snap["headroom_bytes"] == (1 << 20) - want

    def test_account_is_absolute_not_cumulative(self):
        ledger = hvd_memory.HBMLedger(capacity_bytes=None)
        ledger.account("grads", 100)
        ledger.account("grads", 40)  # re-statement, not accumulation
        assert ledger.snapshot()["components"]["grads"] == 40
        assert ledger.total_bytes() == 40

    def test_sharded_leaf_counts_shard_bytes(self):
        mesh = mesh_lib.build_mesh(tp=2)
        w = jax.device_put(jnp.zeros((8, 16), jnp.float32),
                           NamedSharding(mesh, P("tp", None)))
        # committed sharding: one chip holds 4×16 of the 8×16 leaf
        assert hvd_memory.tree_per_chip_bytes({"w": w}) == 4 * 16 * 4

    def test_abstract_tree_shards_by_spec_math(self):
        mesh = mesh_lib.build_mesh(tp=2)
        abstract = {"w": jax.ShapeDtypeStruct((8, 16), jnp.float32),
                    "b": jax.ShapeDtypeStruct((16,), jnp.float32)}
        specs = {"w": P("tp", None), "b": P()}
        got = hvd_memory.tree_per_chip_bytes(abstract, specs, mesh)
        assert got == 4 * 16 * 4 + 16 * 4

    def test_opt_state_bytes_are_adams_two_x(self):
        params = {"w": jnp.zeros((16, 32), jnp.float32)}
        opt = optax.adam(1e-3).init(params)
        ledger = hvd_memory.HBMLedger(capacity_bytes=None)
        ledger.account_tree("opt_state", opt)
        pb = 16 * 32 * 4
        # mu + nu in param dtype, plus the int32 count scalar
        assert ledger.snapshot()["components"]["opt_state"] == 2 * pb + 4

    def test_account_kv_rides_per_chip_bytes(self):
        from horovod_tpu.serving.kv_cache import KVCache
        cfg = tr.TransformerConfig.tiny()
        kv = KVCache(cfg, num_slots=2, max_len=32)
        ledger = hvd_memory.HBMLedger(capacity_bytes=None)
        ledger.account_kv(kv)
        head_dim = cfg.d_model // cfg.num_heads
        want = (2 * cfg.num_layers * 2 * 32 * cfg.num_heads * head_dim
                * jnp.dtype(cfg.dtype).itemsize)
        assert ledger.snapshot()["components"]["kv_cache"] == want

    def test_publish_refreshes_gauges(self, reg):
        ledger = hvd_memory.HBMLedger(capacity_bytes=1000)
        ledger.account("params", 600)
        ledger.account("grads", 100)
        snap = reg.snapshot()
        by_comp = _values(snap, "hvd_hbm_bytes")
        assert by_comp[(("component", "params"),)] == 600
        assert by_comp[(("component", "grads"),)] == 100
        assert _values(snap, "hvd_hbm_capacity_bytes")[()] == 1000
        assert _values(snap, "hvd_hbm_headroom_bytes")[()] == 300


# ---------------------------------------------------------------------------
# plan vs measured: the ≤15% contract on real placed state
# ---------------------------------------------------------------------------

def _measured_components(cfg, mesh):
    """Place real params + adam state through the spec tree and account
    them — the same calls the trainer makes."""
    _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
    specs = tr.param_specs(params)
    tx = optax.adam(1e-3)
    p = trainer.place(params, mesh, specs)
    opt = trainer.init_opt_state(tx, p, mesh, specs)
    ledger = hvd_memory.HBMLedger(capacity_bytes=None)
    ledger.account_tree("params", p)
    ledger.account_tree("opt_state", opt)
    return ledger.snapshot()["components"]


@pytest.mark.parametrize("layout", [dict(), dict(tp=2)],
                         ids=["dp_only", "dp_x_tp2"])
def test_plan_within_15pct_of_measured(layout):
    cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)
    mesh = mesh_lib.build_mesh(**layout)
    measured = _measured_components(cfg, mesh)
    plan = hvd_memory.plan_memory(
        cfg, dp=mesh.shape.get("dp", 1), tp=mesh.shape.get("tp", 1))
    for comp in ("params", "opt_state"):
        got, want = plan["components"][comp], measured[comp]
        assert abs(got - want) <= PLAN_RTOL * want, \
            f"{comp}: planned {got} vs measured {want}"
    # grads mirror params by construction; the plan must say so too
    assert plan["components"]["grads"] == plan["components"]["params"]


def test_plan_tp_shards_params_and_fits_verdict():
    cfg = tr.TransformerConfig.tiny()
    flat = hvd_memory.plan_memory(cfg, chip="cpu")
    split = hvd_memory.plan_memory(cfg, tp=2, chip="cpu")
    assert split["components"]["params"] < flat["components"]["params"]
    assert flat["capacity_bytes"] and flat["fits"] is True
    assert flat["headroom_bytes"] == \
        flat["capacity_bytes"] - flat["total_bytes"]


def test_plan_optimizer_factor_and_kv_math():
    cfg = tr.TransformerConfig.tiny()
    adam = hvd_memory.plan_memory(cfg, optimizer="adam")
    sgd = hvd_memory.plan_memory(cfg, optimizer="sgd")
    none = hvd_memory.plan_memory(cfg, optimizer="none")
    pb = adam["components"]["params"]
    assert adam["components"]["opt_state"] == 2 * pb
    assert sgd["components"]["opt_state"] == pb
    assert none["components"]["opt_state"] == 0
    kv = hvd_memory.plan_memory(cfg, kv_slots=4, kv_max_len=64)
    head_dim = cfg.d_model // cfg.num_heads
    assert kv["components"]["kv_cache"] == (
        2 * cfg.num_layers * 4 * 64 * cfg.num_heads * head_dim
        * jnp.dtype(cfg.dtype).itemsize)


# ---------------------------------------------------------------------------
# compile observability: hit/miss + the storm ladder
# ---------------------------------------------------------------------------

def _args_of_len(n):
    return (jnp.zeros((1, n), jnp.int32),)


class TestCompileTracker:
    def test_hit_miss_accounting(self, reg):
        t = hvd_memory.CompileTracker(min_misses=10 ** 6)
        assert t.observe("train:unit", _args_of_len(8)) == "miss"
        assert t.observe("train:unit", _args_of_len(8)) == "hit"
        assert t.observe("train:unit", _args_of_len(9)) == "miss"
        s = t.site_summary()["train:unit"]
        assert s["hits"] == 1 and s["misses"] == 2
        assert not s["storming"]
        by_outcome = _values(reg.snapshot(), "hvd_compile_total")
        assert by_outcome[(("outcome", "hit"),
                           ("site", "train:unit"))] == 1
        assert by_outcome[(("outcome", "miss"),
                           ("site", "train:unit"))] == 2

    def test_abstract_key_formats_dtype_and_shape(self):
        key = hvd_memory.abstract_key((jnp.zeros((2, 3), jnp.float32),
                                       jnp.zeros((4,), jnp.int32)))
        assert hvd_memory.format_key(key) == "float32[2,3] int32[4]"
        long = hvd_memory.abstract_key(
            tuple(jnp.zeros((i + 1,)) for i in range(10)))
        assert hvd_memory.format_key(long).endswith("...+2")

    def test_first_compile_is_free(self):
        t = hvd_memory.CompileTracker(decay=0.5, threshold=0.1,
                                      min_misses=1)
        t.observe("train:unit", _args_of_len(8))
        assert not t.site_summary()["train:unit"]["storming"]

    def test_storm_escalation_names_site_and_key(self, reg):
        # the escalation evidence is asserted on the EVENT, not caplog:
        # the repo's logging bootstrap puts a handler on the horovod_tpu
        # logger, so caplog capture is suite-order-dependent while the
        # metrics event ring is not
        t = hvd_memory.CompileTracker(decay=0.5, threshold=0.4,
                                      min_misses=3)
        for n in range(6):
            t.observe("serve_prefill", _args_of_len(16 + n))
        s = t.site_summary()["serve_prefill"]
        assert s["storming"] and s["misses"] == 6
        assert "int32[1,21]" in s["last_key"]
        storm = [e for e in reg.events()
                 if e["event"] == "recompile_storm"]
        assert len(storm) == 1
        assert storm[0]["site"] == "serve_prefill"
        assert "int32[1," in storm[0]["key"]
        assert _values(reg.snapshot(), "hvd_recompile_storms_total")[
            (("site", "serve_prefill"),)] == 1

    def test_storm_flight_dump_deduped_per_site(self, reg, tmp_path):
        tracer = hvd_tracing.reset(enabled=True, rank=0)
        tracer._dump_dir = str(tmp_path)
        try:
            t = hvd_memory.CompileTracker(decay=0.5, threshold=0.4,
                                          min_misses=3)
            for n in range(4):  # storm #1 → the one dump
                t.observe("serve_prefill", _args_of_len(16 + n))
            for _ in range(4):  # hits decay the EMA; the storm clears
                t.observe("serve_prefill", _args_of_len(16))
            assert not t.site_summary()["serve_prefill"]["storming"]
            for n in range(4):  # storm #2: event again, dump deduped
                t.observe("serve_prefill", _args_of_len(64 + n))
            assert t.site_summary()["serve_prefill"]["storming"]
            snap = reg.snapshot()
            assert _values(snap, "hvd_recompile_storms_total")[
                (("site", "serve_prefill"),)] == 2
            assert _values(snap, "hvd_flight_dumps_total")[
                (("reason", "recompile_storm"),)] == 1
        finally:
            hvd_tracing.reset()

    def test_instrument_compiles_wrapper(self, reg):
        calls = []
        wrapped = hvd_memory.instrument_compiles(
            lambda x: calls.append(x) or x, site="train:unit")
        wrapped(jnp.zeros((2,)))
        wrapped(jnp.zeros((3,)))
        assert len(calls) == 2  # the wrapped fn always runs
        s = hvd_memory.get_tracker().site_summary()["train:unit"]
        assert s["misses"] == 2

    def test_trainer_step_reports_compile_site(self, reg):
        step = trainer.instrument_step(lambda x: x, name="unit")
        step(jnp.zeros((4,)))
        step(jnp.zeros((4,)))
        s = hvd_memory.get_tracker().site_summary()["train:unit"]
        assert s["misses"] == 1 and s["hits"] == 1


# ---------------------------------------------------------------------------
# GSPMD resharding sentinel
# ---------------------------------------------------------------------------

class TestReshardingSentinel:
    def test_mis_specced_jit_names_leaf_and_axis(self, reg):
        mesh = mesh_lib.build_mesh(tp=2)
        w = jax.device_put(jnp.zeros((8, 16), jnp.float32),
                           NamedSharding(mesh, P("tp", None)))
        # the drill: declared row-sharded, consumed replicated — GSPMD
        # inserts the all-gather the spec tree says shouldn't exist
        bad = jax.jit(lambda x: x * 2.0,
                      in_shardings=NamedSharding(mesh, P("tp", None)),
                      out_shardings=NamedSharding(mesh, P()))
        findings = hvd_memory.scan_jit_resharding(
            bad, (w,), {"w": w}, {"w": P("tp", None)}, mesh,
            site="drill")
        assert len(findings) == 1
        f = findings[0]
        assert f["leaf"] == "['w']" and f["axis"] == "tp"
        assert f["op"] in ("all-gather", "collective-permute")
        assert f["full_shape"] == [8, 16]
        assert f["shard_shape"] == [4, 16]
        events = [e for e in reg.events()
                  if e["event"] == "resharding_finding"]
        assert events and events[0]["leaf"] == "['w']"
        assert _values(reg.snapshot(),
                       "hvd_resharding_findings_total")[
            (("site", "drill"),)] == 1

    def test_clean_gspmd_step_negative_arm(self, reg):
        # the real training step with CORRECT specs must scan silent:
        # activation collectives (psum over dp, tp matmul gathers that
        # match the declared layout) never pair a param leaf's
        # (full, shard) shapes
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                        attention_impl="full")
        model, params = tr.init_params(cfg, jax.random.PRNGKey(0))
        mesh = mesh_lib.build_mesh(tp=2)
        specs = tr.param_specs(params)
        tx = optax.adam(1e-3)
        p = trainer.place(params, mesh, specs)
        opt = trainer.init_opt_state(tx, p, mesh, specs)
        step, _, batch_shard = trainer.make_gspmd_step(
            tr.lm_loss_fn(model), tx, mesh, specs, tr.batch_spec(),
            donate=False, params=p)
        toks = jax.device_put(
            np.zeros((8, 32), np.int32), batch_shard)
        findings = hvd_memory.scan_jit_resharding(
            step, (p, opt, toks), p, specs, mesh, site="gspmd_step")
        assert findings == []
        assert "hvd_resharding_findings_total" not in \
            reg.snapshot()["metrics"]

    def test_hlo_text_parser_matches_param_pair_only(self):
        mesh = mesh_lib.build_mesh(tp=2)
        params = {"w": jax.ShapeDtypeStruct((8, 16), jnp.float32)}
        specs = {"w": P("tp", None)}
        hlo = "\n".join([
            # gathers w's shard back to full: the finding
            "%ag = f32[8,16]{1,0} all-gather(f32[4,16]{1,0} %p0), "
            "replica_groups={{0,1}}, dimensions={0}",
            # an activation all-reduce: same result shape family, no
            # (full, shard) param pair — silent
            "%ar = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %p1)",
            # a batch-shaped gather matching no param leaf — silent
            "%bg = f32[64,32]{1,0} all-gather(f32[32,32]{1,0} %p2), "
            "dimensions={0}",
        ])
        findings = hvd_memory.scan_resharding(hlo, params, specs, mesh,
                                              site="unit")
        assert [f["leaf"] for f in findings] == ["['w']"]
        assert findings[0]["dim"] == 0 and findings[0]["axis"] == "tp"


# ---------------------------------------------------------------------------
# flight dumps + postmortem surfacing
# ---------------------------------------------------------------------------

class TestFlightAndPostmortem:
    def test_flight_snapshot_carries_memory_section(self, reg):
        tracer = hvd_tracing.reset(enabled=True, rank=0)
        try:
            hvd_memory.get_ledger().account("params", 4096)
            hvd_memory.get_tracker().observe("train:unit",
                                             _args_of_len(8))
            snap = tracer.flight_snapshot("unit_test")
            mem = snap["memory"]
            assert mem["hbm"]["components"]["params"] == 4096
            assert mem["compile"]["train:unit"]["misses"] == 1
            import json
            json.dumps(snap)  # dump sections must stay serializable
        finally:
            hvd_tracing.reset()

    def test_flight_section_absent_when_off_or_empty(self):
        assert hvd_memory.flight_section() is None  # nothing accounted
        hvd_memory.get_ledger().account("params", 1)
        assert hvd_memory.flight_section() is not None
        hvd_memory.reset(enabled=False)
        assert hvd_memory.flight_section() is None

    def test_postmortem_surfaces_storms_and_memory(self):
        dump = {
            "version": 1, "rank": 0, "reason": "recompile_storm",
            "ts_us": 10_000, "epoch_us_at_ts0": 1_000_000,
            "spans": [], "open_spans": [], "cycles": [],
            "spans_dropped": 0,
            "events": [
                {"event": "recompile_storm", "site": "serve_prefill",
                 "misses": 9, "key": "int32[1,96]"},
                {"event": "resharding_finding", "site": "gspmd_step",
                 "leaf": "['w']", "op": "all-gather", "axis": "tp"},
            ],
            "memory": {
                "hbm": {"components": {"params": 900},
                        "total_bytes": 900, "capacity_bytes": 1000,
                        "headroom_bytes": 50},
                "compile": {},
            },
            "_path": "flight-rank0.json",
        }
        base = hvd_postmortem.rebase([dump])
        verdict = hvd_postmortem.analyze([dump])
        (storm,) = verdict["recompile_storms"]
        assert storm["site"] == "serve_prefill" and storm["misses"] == 9
        (resh,) = verdict["resharding_findings"]
        assert resh["leaf"] == "['w']" and resh["axis"] == "tp"
        assert verdict["memory_by_rank"][0]["hbm"]["headroom_bytes"] == 50
        text = " ".join(verdict["reasons"])
        assert "serve_prefill" in text and "['w']" in text
        assert "OOM territory" in text
        report = hvd_postmortem.render_report(
            [dump], [], verdict, hvd_postmortem.last_cycles([dump], 8),
            base)
        assert "serve_prefill" in report and "memory at dump time" \
            in report
