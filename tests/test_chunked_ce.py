"""Chunked-vocab cross entropy: numerical identity with the direct
(full-logits) loss, in value AND gradient, including non-dividing chunk
sizes and targets on chunk boundaries."""

import numpy as np
import pytest


@pytest.fixture
def setup(hvd):
    import jax
    import jax.numpy as jnp
    from horovod_tpu.models import transformer as tr

    cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)
    model = tr.TransformerLM(cfg)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 33)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
    return tr, model, params, tokens, cfg


class TestChunkedCE:
    @pytest.mark.parametrize("chunk", [7, 64, 100, 10_000])
    def test_matches_direct_loss(self, setup, chunk):
        import jax
        tr, model, params, tokens, cfg = setup
        direct = tr.lm_loss_fn(model)(params, tokens)
        chunked = tr.lm_loss_fn(model, vocab_chunk=chunk)(params, tokens)
        np.testing.assert_allclose(float(chunked), float(direct),
                                   rtol=1e-5)

    def test_gradients_match(self, setup):
        import jax
        tr, model, params, tokens, cfg = setup
        g_direct = jax.grad(tr.lm_loss_fn(model))(params, tokens)
        g_chunked = jax.grad(
            tr.lm_loss_fn(model, vocab_chunk=50))(params, tokens)
        for (pa, a), (pb, b) in zip(
                jax.tree_util.tree_leaves_with_path(g_direct),
                jax.tree_util.tree_leaves_with_path(g_chunked)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6,
                err_msg=str(pa))

    def test_boundary_targets(self, hvd):
        # every target sits on a chunk edge (first/last id of a chunk)
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        hidden = jnp.asarray(
            np.random.RandomState(1).randn(2, 6, 8), jnp.float32)
        kernel = jnp.asarray(
            np.random.RandomState(2).randn(8, 20), jnp.float32)
        targets = jnp.asarray([[0, 4, 5, 9, 10, 19],
                               [19, 15, 14, 10, 5, 0]], jnp.int32)
        got = tr.chunked_softmax_cross_entropy(hidden, kernel, targets,
                                               chunk=5)
        logits = hidden @ kernel
        logp = jax.nn.log_softmax(logits, axis=-1)
        want = -jnp.mean(jnp.take_along_axis(
            logp, targets[..., None], axis=-1))
        np.testing.assert_allclose(float(got), float(want), rtol=1e-6)

    def test_rejects_nonpositive_chunk(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        with pytest.raises(ValueError, match="positive"):
            tr.chunked_softmax_cross_entropy(
                jnp.ones((1, 2, 4)), jnp.ones((4, 8)),
                jnp.zeros((1, 2), jnp.int32), chunk=0)

    def test_moe_honors_vocab_chunk(self, hvd):
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32, num_experts=2,
                                        num_experts_per_tok=1)
        model = tr.TransformerLM(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 17)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
        direct = tr.lm_loss_fn(model)(params, tokens)
        chunked = tr.lm_loss_fn(model, vocab_chunk=50)(params, tokens)
        np.testing.assert_allclose(float(chunked), float(direct), rtol=1e-5)

    def test_train_step_integration(self, hvd):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu import trainer
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.parallel import mesh as mesh_mod

        mesh = mesh_mod.build_mesh(dp=8)
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)
        model = tr.TransformerLM(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 33)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
        tx = optax.adamw(1e-3)
        specs = tr.param_specs(params)
        step, pshard, bshard = trainer.make_gspmd_step(
            tr.lm_loss_fn(model, vocab_chunk=64), tx, mesh, specs,
            tr.batch_spec(), params=params)
        params = jax.tree_util.tree_map(jax.device_put, params, pshard)
        opt_state = trainer.init_opt_state(tx, params, mesh, specs)
        tokens = jax.device_put(tokens, bshard)
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
