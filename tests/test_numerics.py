"""Numerics plane (utils/numerics.py): one-pass stats math in all three
segment layouts, the fixed-arity batched kernels and their async
park/drain lifecycle, the EMA anomaly policy, digest wire stability,
rank blame, the coordinator's cross-rank divergence sentinel, and the
CycleRequest piggyback end to end over real TCP.

Everything here is single-host CPU; the cross-PROCESS story (a real
divergence drill with flight dumps and a postmortem verdict) lives in
tests/test_chaos_plane.py.
"""

import math
import os

import numpy as np
import pytest

from horovod_tpu.common.config import HorovodConfig
from horovod_tpu.ops import negotiation as neg
from horovod_tpu.run import network
from horovod_tpu.utils import metrics as hvd_metrics
from horovod_tpu.utils import numerics as hvd_numerics
from horovod_tpu.utils import tracing as hvd_tracing

KEY = b"k" * 32


def _val(reg, name, **labels):
    """Read one instrument's value by family name (families register
    once, at monitor/coordinator construction)."""
    fam = reg._families[name]
    return fam.labels(**labels).value if labels else fam.value


def _anomaly_events(reg):
    return [e for e in reg.events() if e.get("event") == "numerics_anomaly"]


@pytest.fixture
def reg():
    """Fresh enabled metrics registry (the monitor binds its instruments
    at construction, so this must precede the monitor fixture)."""
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()


@pytest.fixture
def monitor(reg, tmp_path, monkeypatch):
    """Fresh enabled monitor with deterministic policy knobs and flight
    dumps routed into tmp_path."""
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    hvd_tracing.reset(enabled=True)
    m = hvd_numerics.reset(enabled=True, ema_beta=0.5, ema_k=4.0,
                           warmup=2)
    yield m
    hvd_numerics.reset()
    hvd_tracing.reset()


def _expect_stats(x):
    """Reference stats computed with plain numpy (float64 accumulation
    is fine: the assertions use rel tolerances far above f32 noise)."""
    f = np.asarray(x, np.float64).reshape(-1)
    finite = np.isfinite(f)
    safe = np.where(finite, f, 0.0)
    n = f.size
    return {
        "l2": math.sqrt(float(np.sum(safe * safe))),
        "max_abs": float(np.max(np.abs(safe))) if n else 0.0,
        "nonfinite": float(n - np.count_nonzero(finite)),
        "zero_frac": float(np.count_nonzero(f == 0.0) / n) if n else 0.0,
        "checksum": float(np.sum(safe)),
    }


def _assert_row(row, x, rel=1e-4, abs_tol=1e-4):
    want = _expect_stats(x)
    S = hvd_numerics
    assert float(row[S.S_L2]) == pytest.approx(want["l2"], rel=rel,
                                               abs=abs_tol)
    assert float(row[S.S_MAX_ABS]) == pytest.approx(want["max_abs"],
                                                    rel=1e-5)
    assert float(row[S.S_NONFINITE]) == want["nonfinite"]
    assert float(row[S.S_ZERO_FRAC]) == pytest.approx(want["zero_frac"],
                                                      abs=1e-6)
    assert float(row[S.S_CHECKSUM]) == pytest.approx(want["checksum"],
                                                     rel=rel,
                                                     abs=max(abs_tol, 1e-3))


class TestTensorStats:
    def test_known_values(self):
        s = hvd_numerics.tensor_stats(np.array([3.0, -4.0, 0.0],
                                               np.float32))
        assert float(s["l2"]) == pytest.approx(5.0)
        assert float(s["max_abs"]) == pytest.approx(4.0)
        assert float(s["nonfinite"]) == 0.0
        assert float(s["zero_frac"]) == pytest.approx(1.0 / 3.0)
        assert float(s["checksum"]) == pytest.approx(-1.0)

    def test_nonfinite_counted_but_excluded_from_norms(self):
        x = np.array([np.nan, np.inf, -np.inf, 2.0], np.float32)
        s = hvd_numerics.tensor_stats(x)
        # a NaN burst must not wipe out the norm gauges describing it
        assert float(s["nonfinite"]) == 3.0
        assert float(s["l2"]) == pytest.approx(2.0)
        assert float(s["max_abs"]) == pytest.approx(2.0)

    def test_empty_input_is_all_zero(self):
        s = hvd_numerics.tensor_stats(np.zeros((0,), np.float32))
        assert all(float(v) == 0.0 for v in s.values())

    def test_integer_input_has_no_nonfinites(self):
        s = hvd_numerics.tensor_stats(np.array([[1, -2], [0, 4]],
                                               np.int32))
        assert float(s["nonfinite"]) == 0.0
        assert float(s["max_abs"]) == pytest.approx(4.0)
        assert float(s["zero_frac"]) == pytest.approx(0.25)

    def test_stats_vector_matches_dict_layout(self):
        x = np.array([1.0, np.nan, 0.0, -7.0], np.float32)
        v = np.asarray(hvd_numerics.stats_vector(x))
        assert v.shape == (5,)
        _assert_row(v, x)


class TestSegmentStats:
    def _check_layout(self, sizes, seed=0, rel=1e-4, abs_tol=1e-4):
        rng = np.random.default_rng(seed)
        parts = [rng.standard_normal(s).astype(np.float32) for s in sizes]
        if parts and parts[0].size:
            parts[0][0] = np.nan  # nonfinite lands in slice 0 only
        flat = (np.concatenate(parts) if parts
                else np.zeros((0,), np.float32))
        mat = np.asarray(hvd_numerics.segment_stats(flat, sizes))
        assert mat.shape == (len(sizes), 5)
        for row, part in zip(mat, parts):
            _assert_row(row, part, rel=rel, abs_tol=abs_tol)

    def test_uniform_layout(self):
        # all sizes equal: the no-gather reshape path
        self._check_layout([16] * 8)

    def test_padded_gather_layout(self):
        self._check_layout([3, 17, 1, 30, 9])

    def test_cumsum_fallback_layout(self):
        # one huge slice beside tiny ones: n * max_s blows the padding
        # budget, forcing the cumsum-difference + segment_max path
        sizes = [8192] + [2] * 40
        assert len(sizes) * max(sizes) > max(4 * sum(sizes), 4096)
        # loose ABSOLUTE tolerance: a tiny segment's sum-of-squares
        # comes out as the difference of two large f32 cumulative sums,
        # so the error scales with the whole buffer, not the segment
        # (cancellation is the price of the memory-bounded fallback)
        self._check_layout(sizes, rel=5e-3, abs_tol=2e-2)

    def test_empty_segment_among_real_ones(self):
        rng = np.random.default_rng(1)
        flat = rng.standard_normal(8).astype(np.float32)
        mat = np.asarray(hvd_numerics.segment_stats(flat, [5, 0, 3]))
        _assert_row(mat[0], flat[:5])
        # the empty slice reads as all-zero, never -inf/NaN
        assert np.all(np.isfinite(mat[1])) and np.all(mat[1] == 0.0)
        _assert_row(mat[2], flat[5:])

    def test_layouts_agree_with_each_other(self):
        # the uniform and padded-gather impls are interchangeable: same
        # logical slices, same rows
        rng = np.random.default_rng(2)
        flat = rng.standard_normal(64).astype(np.float32)
        uniform = np.asarray(hvd_numerics.segment_stats(flat, [16] * 4))
        padded = np.asarray(hvd_numerics.segment_stats(
            np.concatenate([flat, np.zeros(2, np.float32)]),
            [16, 16, 16, 16, 2]))[:4]
        np.testing.assert_allclose(uniform, padded, rtol=1e-5, atol=1e-6)


class TestBatchedKernels:
    def test_batch_stats_matches_per_tensor(self):
        rng = np.random.default_rng(3)
        arrays = [rng.standard_normal((4, 8)).astype(np.float32)
                  for _ in range(3)]
        arrays.append(np.full((7,), np.inf, np.float32))  # second shape
        arrays.append(rng.standard_normal(5).astype(np.float64))
        mat = hvd_numerics._batch_stats(arrays)
        assert mat.shape == (5, 5)
        for row, a in zip(mat, arrays):
            _assert_row(row, a)

    def test_pow2_padding_rows_never_leak(self):
        # 3 same-shape arrays ride a 4-ary kernel; the zero padding row
        # must be sliced off before the caller sees anything
        arrays = [np.full((6,), float(i + 1), np.float32)
                  for i in range(3)]
        groups = list(hvd_numerics._batch_stats_groups(arrays))
        assert len(groups) == 1
        idxs, k, dev = groups[0]
        assert idxs == [0, 1, 2] and k == 3
        assert np.asarray(dev).shape == (4, 5)  # padded on device...
        mat = hvd_numerics._batch_stats(arrays)
        assert mat.shape == (3, 5)              # ...sliced at the host
        for row, a in zip(mat, arrays):
            _assert_row(row, a)

    def test_kernel_cache_keys_are_pow2_not_batch_layout(self):
        # racy flush splits must not compile fresh kernels: any group of
        # 5..8 same-shape tensors lands on the same 8-ary kernel
        fn = hvd_numerics._group_stats_fn
        assert fn(8, (6,)) is fn(8, (6,))
        for k in (5, 6, 7, 8):
            arrays = [np.ones((6,), np.float32)] * k
            ((_, got_k, dev),) = hvd_numerics._batch_stats_groups(arrays)
            assert got_k == k and np.asarray(dev).shape == (8, 5)

    def test_mixed_shapes_group_independently(self):
        arrays = [np.ones((4,), np.float32), np.ones((2, 2), np.float32),
                  np.ones((4,), np.float32)]
        groups = {tuple(idxs) for idxs, _, _ in
                  hvd_numerics._batch_stats_groups(arrays)}
        assert groups == {(0, 2), (1,)}


class TestMonitorObserve:
    def test_local_path_is_async_and_drain_forces(self, monitor, reg):
        g = np.array([3.0, 4.0], np.float32)
        out = monitor.observe([("w", g, None)])
        assert out == {}  # local path never builds wire records
        monitor.drain()   # force the parked kernel result in
        assert _val(reg, "hvd_grad_norm", tensor="w") == pytest.approx(5.0)
        assert _val(reg, "hvd_numerics_tensors_observed_total") == 1

    def test_gauges_lag_by_at_most_one_drain(self, monitor, reg):
        # the async contract: after N observes plus one drain, all N
        # tensors' gauges are live (nothing is lost, only deferred)
        for i in range(4):
            monitor.observe([(f"t{i}", np.full((3,), float(i + 1),
                                               np.float32), None)])
        monitor.drain()
        for i in range(4):
            assert _val(reg, "hvd_grad_norm", tensor=f"t{i}") > 0.0

    def test_digest_path_returns_mirrored_records(self, monitor):
        g = np.array([1.0, -1.0, 0.0, np.nan], np.float32)
        recs = monitor.observe([("w", g, None)], cycle=7)
        R = hvd_numerics
        rec = recs["w"]
        assert len(rec) == 7
        # single-process: the reduced copy IS the local contribution
        assert rec[R.R_RED_L2] == rec[R.R_LOC_L2]
        assert rec[R.R_RED_NONFINITE] == rec[R.R_LOC_NONFINITE] == 1
        assert rec[R.R_RED_L2] == pytest.approx(math.sqrt(2.0), rel=1e-4)

    def test_digest_path_with_distinct_reduced_side(self, monitor):
        loc = np.array([2.0, 0.0], np.float32)
        red = np.array([8.0, 6.0], np.float32)
        rec = monitor.observe([("w", loc, red)], cycle=1)["w"]
        R = hvd_numerics
        assert rec[R.R_RED_L2] == pytest.approx(10.0, rel=1e-4)
        assert rec[R.R_LOC_L2] == pytest.approx(2.0, rel=1e-4)

    def test_ingest_builds_records_only_with_cycle(self, monitor):
        mat = np.asarray([[1.0, 1.0, 0.0, 0.0, 1.0]], np.float32)
        assert monitor.ingest(["w"], mat) == {}
        assert "w" in monitor.ingest(["w"], mat, cycle=3)

    def test_empty_observe_is_a_noop(self, monitor):
        assert monitor.observe([]) == {}
        assert monitor.observe([], cycle=1) == {}


class TestAnomalyPolicy:
    def test_nonfinite_flags_event_and_counter(self, monitor, reg,
                                               tmp_path):
        g = np.array([np.nan, 1.0, np.inf], np.float32)
        monitor.observe([("w", g, None)], cycle=2)
        evs = _anomaly_events(reg)
        assert len(evs) == 1
        ev = evs[0]
        assert ev["anomaly"] == hvd_numerics.ANOMALY_NONFINITE
        assert ev["tensor"] == "w" and ev["cycle"] == 2
        assert ev["nonfinite_local"] == 2
        assert _val(reg, "hvd_nonfinite_total", tensor="w",
                    where="local") == 2
        # the escalation wrote exactly one flight dump
        assert list(tmp_path.glob("flight-rank*.json"))

    def test_norm_spike_trips_after_warmup(self, monitor, reg):
        # warmup=2, ema_k=4: two calm steps arm the policy, then a 100x
        # spike trips it
        calm = np.ones((4,), np.float32)
        for c in range(3):
            monitor.observe([("w", calm, None)], cycle=c)
        monitor.observe([("w", calm * 100.0, None)], cycle=3)
        evs = _anomaly_events(reg)
        assert len(evs) == 1
        assert evs[0]["anomaly"] == hvd_numerics.ANOMALY_NORM_SPIKE
        assert evs[0]["l2"] == pytest.approx(200.0)
        assert evs[0]["ema"] == pytest.approx(2.0)
        # the drift gauge reads post-update: the spike is already folded
        # into the EMA (beta=0.5 -> ema 101), so drift = 200/101
        assert _val(reg, "hvd_grad_norm_drift",
                    tensor="w") == pytest.approx(200.0 / 101.0, rel=1e-5)

    def test_spike_policy_disarmed_during_warmup(self, monitor, reg):
        monitor.observe([("w", np.ones((4,), np.float32), None)], cycle=0)
        monitor.observe([("w", np.full((4,), 1e4, np.float32), None)],
                        cycle=1)
        assert not _anomaly_events(reg)

    def test_all_zero_warmup_never_flags_first_real_gradient(
            self, monitor, reg):
        z = np.zeros((4,), np.float32)
        for c in range(5):
            monitor.observe([("w", z, None)], cycle=c)
        monitor.observe([("w", np.ones((4,), np.float32) * 50.0, None)],
                        cycle=5)
        assert not _anomaly_events(reg)

    def test_anomaly_deduped_per_tensor_and_kind(self, monitor, reg):
        bad = np.array([np.nan], np.float32)
        for c in range(4):
            monitor.observe([("w", bad, None)], cycle=c)
        assert len(_anomaly_events(reg)) == 1  # a persistent NaN must
        # not flood the event ring — but the raw counter keeps counting
        assert _val(reg, "hvd_nonfinite_total", tensor="w",
                    where="local") == 4


class TestDigestWire:
    def test_round_is_stable_at_six_digits(self):
        assert hvd_numerics._round(1.23456789) == 1.23457
        assert hvd_numerics._round(0.1 + 0.2) == 0.3
        # two ranks arriving at the same value through different float
        # histories encode the same wire number
        assert hvd_numerics._round(sum([0.1] * 10)) == \
            hvd_numerics._round(1.0)

    def test_fold_digest_accumulates_cycles(self):
        d = hvd_numerics.fold_digest(None, 3, {"a": (1,) * 7}, rank=2)
        d = hvd_numerics.fold_digest(d, 3, {"b": (2,) * 7}, rank=2)
        d = hvd_numerics.fold_digest(d, 4, {"a": (3,) * 7}, rank=2)
        assert d["v"] == hvd_numerics.DIGEST_VERSION and d["rank"] == 2
        assert sorted(d["cycles"]) == [3, 4]
        assert sorted(d["cycles"][3]) == ["a", "b"]

    def test_fold_digest_empty_records_change_nothing(self):
        assert hvd_numerics.fold_digest(None, 1, {}, rank=0) is None

    def test_records_disagree_tolerance(self):
        a = (10.0, 2.0, 0, 5.0, 10.0, 2.0, 0)
        within = (10.0 * (1 + 5e-5), 2.0, 0, 5.0, 99.0, 2.0, 0)
        beyond = (10.0 * 1.01, 2.0, 0, 5.0, 10.0, 2.0, 0)
        assert not hvd_numerics.records_disagree(a, within, tol=1e-4)
        assert hvd_numerics.records_disagree(a, beyond, tol=1e-4)
        # local columns are evidence for blame, not for disagreement
        assert not hvd_numerics.records_disagree(
            a, (10.0, 2.0, 0, 5.0, 77.0, 9.0, 0), tol=1e-4)

    def test_records_disagree_on_any_nonfinite_mismatch(self):
        a = (10.0, 2.0, 0, 5.0, 10.0, 2.0, 0)
        b = (10.0, 2.0, 1, 5.0, 10.0, 2.0, 1)
        assert hvd_numerics.records_disagree(a, b, tol=1e9)

    def test_blame_prefers_local_nonfinite_carrier(self):
        recs = {0: (1.0, 1.0, 1, 1.0, 1.0, 1.0, 0),
                2: (1.0, 1.0, 1, 1.0, 1.0, 1.0, 3),
                1: (1.0, 1.0, 1, 1.0, 1.0, 1.0, 0)}
        assert hvd_numerics.blame_rank(recs) == 2

    def test_blame_picks_local_l2_outlier(self):
        def rec(loc_l2):
            return (5.0, 1.0, 0, 2.0, loc_l2, 1.0, 0)
        assert hvd_numerics.blame_rank(
            {0: rec(1.0), 1: rec(1.1), 2: rec(40.0), 3: rec(0.9)}) == 2

    def test_blame_is_deterministic_and_total(self):
        assert hvd_numerics.blame_rank({}) is None
        one = {5: (1.0, 1.0, 0, 1.0, 1.0, 1.0, 0)}
        assert hvd_numerics.blame_rank(one) == 5


def _digest(rank, cycle, name, loc_l2, nonfinite=0):
    rec = (hvd_numerics._round(loc_l2), 1.0, int(nonfinite),
           hvd_numerics._round(loc_l2), hvd_numerics._round(loc_l2),
           1.0, int(nonfinite))
    return hvd_numerics.fold_digest(None, cycle, {name: rec}, rank=rank)


class TestCoordinatorSentinel:
    """The sentinel itself, driven through the real request handler
    (no sockets: _handle is what the TCP layer calls)."""

    def _service(self, nproc=2):
        cfg = HorovodConfig(fusion_threshold=0,
                            stall_warning_time_seconds=0)
        return neg.CoordinatorService(nproc, KEY, ports=[0], config=cfg)

    def test_agreeing_digests_stay_quiet(self, reg):
        svc = self._service()
        try:
            for r in range(2):
                svc._handle(neg.CycleRequest(
                    r, [], -1, req_id=1,
                    digest=_digest(r, 0, "g", 3.0)), ("", 0))
            assert not svc._numerics_flagged
            assert _val(reg, "hvd_numerics_divergent_rank") == -1
        finally:
            svc.shutdown()

    def test_divergent_digest_names_rank_tensor_cycle(self, reg,
                                                      monkeypatch,
                                                      tmp_path):
        monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
        hvd_tracing.reset(enabled=True)
        svc = self._service(nproc=3)
        try:
            # cycles 0-1 healthy everywhere; rank 1 diverges at cycle 2.
            # The divergent rank reports LAST each cycle: blame needs a
            # 3-holder median (a 2-holder split is symmetric — neither
            # side is the outlier yet)
            for cyc in range(3):
                for r in (0, 2, 1):
                    l2 = 9.0 if (r == 1 and cyc >= 2) else 3.0
                    svc._handle(neg.CycleRequest(
                        r, [], -1, req_id=cyc + 1,
                        digest=_digest(r, cyc, "g", l2)), ("", 0))
            key = (2, "g", hvd_numerics.ANOMALY_DIVERGENCE)
            assert key in svc._numerics_flagged
            assert svc._numerics_flagged[key] == 1
            assert svc._numerics_first_bad["g"] == 2
            assert _val(reg, "hvd_numerics_divergent_rank") == 1
            evs = _anomaly_events(reg)
            assert evs and evs[0]["divergent_rank"] == 1
            assert evs[0]["tensor"] == "g"
            assert evs[0]["first_bad_cycle"] == 2
        finally:
            svc.shutdown()
            hvd_tracing.reset()

    def test_nonfinite_digest_blames_the_carrier(self, reg):
        svc = self._service()
        try:
            svc._handle(neg.CycleRequest(
                0, [], -1, req_id=1,
                digest=_digest(0, 5, "g", 3.0)), ("", 0))
            svc._handle(neg.CycleRequest(
                1, [], -1, req_id=1,
                digest=_digest(1, 5, "g", 3.0, nonfinite=2)), ("", 0))
            key = (5, "g", hvd_numerics.ANOMALY_NONFINITE)
            assert svc._numerics_flagged.get(key) == 1
            assert _val(reg, "hvd_coordinator_numerics_anomalies_total",
                        kind=hvd_numerics.ANOMALY_NONFINITE) >= 1
        finally:
            svc.shutdown()

    def test_digest_store_bounded_by_window(self, reg, monkeypatch):
        monkeypatch.setenv("HVD_NUMERICS_DIGEST_CYCLES", "4")
        svc = self._service(nproc=1)
        try:
            for cyc in range(10):
                svc._handle(neg.CycleRequest(
                    0, [], -1, req_id=cyc + 1,
                    digest=_digest(0, cyc, "g", 1.0)), ("", 0))
            assert len(svc._digests) == 4
            assert min(svc._digests) == 6
        finally:
            svc.shutdown()

    def test_unversioned_digest_is_ignored(self, reg):
        svc = self._service(nproc=1)
        try:
            svc._handle(neg.CycleRequest(
                0, [], -1, req_id=1, digest={"v": 999, "cycles": {
                    0: {"g": (1.0,) * 7}}}), ("", 0))
            svc._handle(neg.CycleRequest(
                0, [], -1, req_id=2, digest="not a digest"), ("", 0))
            assert not svc._digests
        finally:
            svc.shutdown()


class TestPiggybackTransport:
    def test_digest_rides_a_real_tcp_cycle(self, reg):
        """CycleRequest.digest over a live socket: the worker attaches
        the digest the monitor built, the coordinator's sentinel sees it
        (same transport pattern as the metrics snapshot)."""
        cfg = HorovodConfig(fusion_threshold=0,
                            stall_warning_time_seconds=0)
        svc = neg.CoordinatorService(1, KEY, ports=[0], config=cfg)
        try:
            c = network.BasicClient(neg.SERVICE_NAME,
                                    {"local": [("127.0.0.1", svc.port)]},
                                    KEY)
            m = neg.EntryMeta("g", "allreduce", "float32", (4,), 0, False)
            c.request(neg.CycleRequest(
                0, [m], -1, req_id=1,
                digest=_digest(0, 0, "g", 2.0, nonfinite=1)))
            assert 0 in svc._digests and "g" in svc._digests[0][0]
            key = (0, "g", hvd_numerics.ANOMALY_NONFINITE)
            assert svc._numerics_flagged.get(key) == 0
            c.close()
        finally:
            svc.shutdown()


class TestNullMonitor:
    def test_disabled_monitor_is_inert(self, reg):
        m = hvd_numerics.reset(enabled=False)
        try:
            assert not m.enabled
            assert m.observe([("w", np.array([np.nan], np.float32),
                               None)], cycle=1) == {}
            assert m.ingest(["w"], np.ones((1, 5), np.float32)) == {}
            assert m.drain() is None
            m.observe_compression("w", np.ones(2), np.ones(2), "fp16")
            assert not _anomaly_events(reg)
        finally:
            hvd_numerics.reset()

    def test_env_gate_selects_null(self, monkeypatch):
        monkeypatch.setenv("HVD_NUMERICS", "0")
        try:
            m = hvd_numerics.reset()
            assert isinstance(m, hvd_numerics.NullMonitor)
        finally:
            monkeypatch.delenv("HVD_NUMERICS")
            hvd_numerics.reset()

    def test_default_is_enabled(self):
        assert "HVD_NUMERICS" not in os.environ
        assert "HOROVOD_NUMERICS" not in os.environ
        assert hvd_numerics.numerics_enabled()


class TestCompressionDelta:
    def test_relative_norm_delta_gauge(self, monitor, reg):
        before = np.array([3.0, 4.0], np.float32)  # l2 = 5
        after = np.array([3.0, 0.0], np.float32)   # l2 = 3
        monitor.observe_compression("w", before, after, "topk")
        assert _val(reg, "hvd_compression_norm_delta", tensor="w",
                    compressor="topk") == pytest.approx(0.4, rel=1e-5)
        assert _val(reg, "hvd_compressed_tensors_total",
                    compressor="topk") == 1

    def test_zero_norm_input_reports_zero_delta(self, monitor, reg):
        z = np.zeros((3,), np.float32)
        monitor.observe_compression("z", z, z, "fp16")
        assert _val(reg, "hvd_compression_norm_delta", tensor="z",
                    compressor="fp16") == 0.0
