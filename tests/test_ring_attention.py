"""Ring attention and Ulysses sequence parallelism vs the exact reference
attention — numerics must match, not approximate (SURVEY.md §5 extension;
no upstream equivalent exists)."""

import numpy as np
import pytest

from horovod_tpu.common import compat


def _make_qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, s, h, d)
    return (rng.randn(*shape).astype(np.float32) * 0.3,
            rng.randn(*shape).astype(np.float32) * 0.3,
            rng.randn(*shape).astype(np.float32) * 0.3)


def _run_sp(hvd, fn, q, k, v, n_sp=8):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()[:n_sp]), ("sp",))
    return jax.jit(compat.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp")))(q, k, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(hvd, causal):
    from horovod_tpu.parallel import ring
    q, k, v = _make_qkv()
    expect = ring.full_attention(q, k, v, causal=causal)
    got = _run_sp(hvd, lambda a, b, c: ring.ring_attention(
        a, b, c, axis_name="sp", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_long_sequence_shards(hvd):
    # sequence 128 over 8 shards — each worker only ever holds 16 positions
    from horovod_tpu.parallel import ring
    q, k, v = _make_qkv(b=1, s=128, h=2, d=4, seed=1)
    expect = ring.full_attention(q, k, v, causal=True)
    got = _run_sp(hvd, lambda a, b, c: ring.ring_attention(a, b, c),
                  q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_full(hvd, causal):
    from horovod_tpu.parallel import ring
    q, k, v = _make_qkv(h=8)  # heads divisible by sp=8
    expect = ring.full_attention(q, k, v, causal=causal)
    got = _run_sp(hvd, lambda a, b, c: ring.ulysses_attention(
        a, b, c, axis_name="sp", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_head_divisibility_check(hvd):
    import jax
    from horovod_tpu.parallel import ring
    q, k, v = _make_qkv(h=4)  # 4 heads, sp=8 → error
    with pytest.raises(AssertionError):
        _run_sp(hvd, lambda a, b, c: ring.ulysses_attention(a, b, c),
                q, k, v)


def test_ring_attention_grad_flows(hvd):
    """Gradient through ring attention is finite and matches full-attention
    gradient."""
    import jax
    import jax.numpy as jnp
    from horovod_tpu.parallel import ring
    q, k, v = _make_qkv(b=1, s=16, h=2, d=4)

    def loss_ring(q, k, v):
        return jnp.sum(ring.ring_attention(q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(ring.full_attention(q, k, v) ** 2)

    g_full = jax.grad(loss_full)(q, k, v)

    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    g_ring = jax.jit(compat.shard_map(
        jax.grad(loss_ring, argnums=0), mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp")))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=1e-4, atol=1e-4)


class TestRingFlash:
    """ring_flash_attention: the ring with the Pallas flash kernel as
    the per-pair engine (fwd + custom-vjp bwd) — numerics must match the
    exact full attention, like ring_attention."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full(self, hvd, causal):
        from horovod_tpu.parallel import ring
        q, k, v = _make_qkv()
        expect = ring.full_attention(q, k, v, causal=causal)
        got = _run_sp(hvd, lambda a, b, c: ring.ring_flash_attention(
            a, b, c, axis_name="sp", causal=causal), q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    def test_long_sequence_shards(self, hvd):
        from horovod_tpu.parallel import ring
        q, k, v = _make_qkv(b=1, s=128, h=2, d=4, seed=1)
        expect = ring.full_attention(q, k, v, causal=True)
        got = _run_sp(hvd, lambda a, b, c: ring.ring_flash_attention(
            a, b, c), q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_full(self, hvd, causal):
        """dq/dk/dv through the two-ring custom vjp vs autodiff of the
        exact full attention."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from horovod_tpu.parallel import ring
        q, k, v = _make_qkv(b=1, s=32, h=2, d=4, seed=3)

        def loss_full(q, k, v):
            return jnp.sum(ring.full_attention(q, k, v,
                                               causal=causal) ** 2)

        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)

        def loss_ring(q, k, v):
            return jnp.sum(ring.ring_flash_attention(
                q, k, v, causal=causal) ** 2)

        mesh = Mesh(np.asarray(jax.devices()), ("sp",))
        g_ring = jax.jit(compat.shard_map(
            jax.grad(loss_ring, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=(P(None, "sp"),) * 3))(q, k, v)
        for got, want, name in zip(g_ring, g_full, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4,
                err_msg=f"d{name} mismatch")


def test_ulysses_grad_matches_full(hvd):
    """Ulysses gradients (plain autodiff through the all-to-alls) vs the
    full-attention gradient — completing the values-AND-gradients
    coverage claim for all three sp attention variants."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.parallel import ring
    q, k, v = _make_qkv(b=1, s=32, h=8, d=4, seed=5)

    def loss_full(q, k, v):
        return jnp.sum(ring.full_attention(q, k, v) ** 2)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)

    def loss_uly(q, k, v):
        return jnp.sum(ring.ulysses_attention(q, k, v) ** 2)

    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    g_uly = jax.jit(compat.shard_map(
        jax.grad(loss_uly, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, "sp"),) * 3,
        out_specs=(P(None, "sp"),) * 3))(q, k, v)
    for got, want, name in zip(g_uly, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} mismatch")


class TestRingFlashWireVolume:
    def test_hlo_one_kv_block_per_hop_no_seq_allgather(self, hvd):
        """The perf contract of the ring (SURVEY §5 long-context): the
        COMPILED forward+backward step moves K/V (and in backward their
        grad partials) around the ring one LOCAL block per hop via
        collective-permute, and never all-gathers the sequence. Same
        compiled-HLO methodology as
        test_parallel.py::test_hierarchical_allreduce_hlo_reduces_slow_axis_bytes.

        Expected collective-permutes for W ring steps (python-unrolled
        ring, parallel/ring.py): forward 2·W (k, v) + backward 4·W
        (k, v, dk, dv) = 6·W, every one carrying exactly the local
        [b, s/W, h, d] block."""
        import re

        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from horovod_tpu.parallel import ring

        b, s, h, d = 2, 64, 4, 8
        W = 8
        q, k, v = _make_qkv(b=b, s=s, h=h, d=d)
        mesh = Mesh(np.asarray(jax.devices()[:W]), ("sp",))

        def loss(a, bb, c):
            out = ring.ring_flash_attention(a, bb, c, axis_name="sp",
                                            causal=True)
            return jnp.sum(out.astype(jnp.float32))

        grad = jax.grad(loss, argnums=(0, 1, 2))
        j = jax.jit(compat.shard_map(
            grad, mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"))))
        hlo = j.lower(q, k, v).compile().as_text()

        block_elems = b * (s // W) * h * d
        permutes = []
        for m in re.finditer(
                r"(\w+)\[([\d,]*)\][^=]*collective-permute\(", hlo):
            dims = [int(x) for x in m.group(2).split(",") if x]
            elems = int(np.prod(dims)) if dims else 1
            permutes.append((m.group(1), elems))
        assert permutes, "no collective-permute in compiled ring HLO"
        for dtype, elems in permutes:
            assert elems <= block_elems, (
                f"a ring hop moves {elems} elements — more than one "
                f"local K/V block ({block_elems}): {permutes}")
        # Total wire volume. Textbook ring fwd+bwd is 6W blocks (k, v
        # fwd; k, v, dk, dv bwd). The compiled graph currently does
        # better — XLA CSEs the backward's k/v rotation against the
        # forward's and DCEs the final unused k/v hop, leaving
        # 2(W-1) + 2W = 30 blocks here — but that exact count is XLA's
        # choice, not our contract. Assert the CONTRACT bounds: no more
        # than the textbook volume (i.e. nothing extra got gathered or
        # re-sent), and at least the information-theoretic floor (k and
        # v must each visit W-1 other ranks; dk/dv partials must each
        # travel home, W-1 hops minimum).
        total = sum(e for _, e in permutes)
        lo = 4 * (W - 1) * block_elems
        hi = 6 * W * block_elems
        assert lo <= total <= hi, (
            f"ring moves {total} elements, outside the contract bounds "
            f"[{lo}, {hi}] ({block_elems}-element blocks, W={W})")
        # and the sequence is never all-gathered
        for m in re.finditer(r"\w+\[([\d,]*)\][^=]*all-gather\(", hlo):
            dims = [int(x) for x in m.group(1).split(",") if x]
            elems = int(np.prod(dims)) if dims else 1
            assert elems < b * s * h * d, (
                f"all-gather of {elems} elements >= full sequence "
                f"({b * s * h * d}) — the ring must not gather K/V")
