"""Run-history plane (utils/history.py): the on-disk metrics WAL.

Covers the wire format (full/delta segments, exact-once event capture,
torn-tail tolerance), segment rotation and pruning under the size
budget, the rank-0 run manifest, reader rematerialization, and the
writer-death contract (the first write failure kills the writer, never
the run).
"""

import json
import os
import shutil

import pytest

from horovod_tpu.utils import history as hvd_history
from horovod_tpu.utils import metrics as hvd_metrics


@pytest.fixture
def reg():
    """Standalone registry so tests never touch the process singleton."""
    return hvd_metrics.MetricsRegistry(rank=0)


def _writer(tmp_path, reg, **kw):
    kw.setdefault("interval_s", 3600.0)  # only explicit flushes record
    return hvd_history.HistoryWriter(str(tmp_path), registry=reg, **kw)


class TestWireFormat:
    def test_segment_opens_full_then_deltas(self, tmp_path, reg):
        c = reg.counter("t_steps")
        w = _writer(tmp_path, reg)
        try:
            c.inc()
            w.flush(wait=True)
            c.inc()
            w.flush(wait=True)
        finally:
            w.close()
        records, torn = hvd_history.read_records(str(tmp_path), rank=0)
        assert torn == 0
        # close() appends one final record after the two flushes
        assert [r["t"] for r in records] == ["full", "delta", "delta"]
        assert [r["seq"] for r in records] == [0, 1, 2]
        # The delta carries the changed counter but not the writer's
        # own never-changing instruments... all counters change here, so
        # instead assert deltas shrink: an untouched gauge drops out.
        g = reg.gauge("t_idle")
        g.set(5.0)
        w2 = _writer(tmp_path, reg)
        try:
            w2.flush(wait=True)   # full: includes t_idle
            c.inc()               # t_idle untouched
            w2.flush(wait=True)
        finally:
            w2.close()
        records, _ = hvd_history.read_records(str(tmp_path), rank=0)
        # records[-1] is w2's close() record; the flush pair precedes it
        full, delta = records[-3], records[-2]
        assert "t_idle" in full["metrics"]
        assert "t_idle" not in delta["metrics"]
        assert "t_steps" in delta["metrics"]

    def test_delta_round_trip_rematerializes_exact_state(self, tmp_path,
                                                         reg):
        c = reg.counter("t_tokens")
        g = reg.gauge("t_hbm", labels=("chip",))
        w = _writer(tmp_path, reg)
        try:
            for i in range(5):
                c.inc(10)
                g.labels(chip=str(i % 2)).set(float(i))
                w.flush(wait=True)
        finally:
            w.close()
        records, torn = hvd_history.read_records(str(tmp_path), rank=0)
        assert torn == 0
        states = list(hvd_history.iter_states(records))
        assert len(states) == 6  # 5 flushes + the close() record
        final = states[-1]["metrics"]
        assert final["t_tokens"]["values"][0]["value"] == 50.0
        # series() walks the overlay per record
        pts = hvd_history.series(records, "t_tokens")
        assert [v for _, v in pts] == \
            [10.0, 20.0, 30.0, 40.0, 50.0, 50.0]
        pts0 = hvd_history.series(records, "t_hbm", labels={"chip": "0"})
        assert pts0[-1][1] == 4.0

    def test_event_capture_is_exact_once(self, tmp_path, reg):
        w = _writer(tmp_path, reg)
        try:
            reg.event("phase", name="warmup")
            w.flush(wait=True)
            reg.event("phase", name="train")
            reg.event("phase", name="drain")
            w.flush(wait=True)
            w.flush(wait=True)  # nothing new: no duplicate events
        finally:
            w.close()
        records, _ = hvd_history.read_records(str(tmp_path), rank=0)
        events, missed = hvd_history.read_events(records)
        assert missed == 0
        assert [e["name"] for e in events
                if e["event"] == "phase"] == ["warmup", "train", "drain"]

    def test_ring_overflow_is_counted_as_missed(self, tmp_path, reg):
        w = _writer(tmp_path, reg)
        try:
            n = hvd_metrics.MetricsRegistry.EVENT_RING + 40
            for i in range(n):
                reg.event("burst", i=i)
            w.flush(wait=True)
        finally:
            w.close()
        records, _ = hvd_history.read_records(str(tmp_path), rank=0)
        events, missed = hvd_history.read_events(records)
        assert missed == 40
        assert len([e for e in events if e["event"] == "burst"]) == \
            hvd_metrics.MetricsRegistry.EVENT_RING
        # The captured slice is the ring tail, not its head.
        assert events[-1]["i"] == n - 1

    def test_torn_tail_is_skipped_and_counted(self, tmp_path, reg):
        c = reg.counter("t_c")
        w = _writer(tmp_path, reg)
        try:
            c.inc()
            w.flush(wait=True)
            c.inc()
            w.flush(wait=True)
        finally:
            w.close()
        seg = tmp_path / "history-rank0-000000.jsonl"
        with open(seg, "a") as f:
            f.write('{"v": 1, "t": "delta", "seq": 2, "metr')  # crash tear
        records, torn = hvd_history.read_records(str(tmp_path), rank=0)
        assert torn == 1
        assert [r["seq"] for r in records] == [0, 1, 2]


class TestRotation:
    def _bulky(self, reg):
        fam = reg.gauge("t_bulk", labels=("k",))
        return [fam.labels(k=f"key-{i:04d}") for i in range(120)]

    def test_segments_rotate_at_quarter_budget(self, tmp_path, reg):
        kids = self._bulky(reg)
        # max_bytes floors at 64 KiB -> rotate every 16 KiB; each record
        # rewrites every child (~8 KiB) so rotation happens quickly.
        w = _writer(tmp_path, reg, max_mb=0.001)
        try:
            for step in range(6):
                for kid in kids:
                    kid.set(float(step))
                w.flush(wait=True)
        finally:
            w.close()
        segs = sorted(p.name for p in tmp_path.glob("history-rank0-*.jsonl"))
        assert len(segs) >= 2
        # Every segment is self-contained: it opens with a full record.
        for name in segs:
            first = json.loads(
                (tmp_path / name).read_text().splitlines()[0])
            assert first["t"] == "full"
        assert w._m_rot.value >= 1

    def test_oldest_segments_pruned_to_budget(self, tmp_path, reg):
        kids = self._bulky(reg)
        w = _writer(tmp_path, reg, max_mb=0.001)
        try:
            for step in range(40):
                for kid in kids:
                    kid.set(float(step))
                w.flush(wait=True)
        finally:
            w.close()
        segs = sorted(p.name for p in tmp_path.glob("history-rank0-*.jsonl"))
        assert len(segs) <= hvd_history.SEGMENTS_KEPT
        # seq 000000 rolled off; the survivors are the newest.
        assert "history-rank0-000000.jsonl" not in segs
        # Reconstruction still works from the surviving window.
        records, torn = hvd_history.read_records(str(tmp_path), rank=0)
        assert torn == 0
        states = list(hvd_history.iter_states(records))
        assert states[-1]["metrics"]["t_bulk"]["values"][0]["value"] == 39.0


class TestManifest:
    def test_rank0_writes_provenance_manifest(self, tmp_path, reg):
        w = _writer(tmp_path, reg)
        w.close()
        man = hvd_history.load_manifest(str(tmp_path))
        assert man is not None
        assert man["version"] == hvd_history.HISTORY_VERSION
        prov = man["provenance"]
        for key in ("unix_ms", "platform", "device_kind", "git_sha"):
            assert key in prov

    def test_annotate_merges_context_and_keeps_run_start(self, tmp_path,
                                                         reg):
        w = _writer(tmp_path, reg)
        try:
            started = hvd_history.load_manifest(str(tmp_path))
            w.annotate(label="drill-a", fleet="canary")
        finally:
            w.close()
        man = hvd_history.load_manifest(str(tmp_path))
        assert man["fleet"] == "canary"
        assert man["provenance"]["label"] == "drill-a"
        assert man["run_id"] == started["run_id"]
        assert man["provenance"]["unix_ms"] == \
            started["provenance"]["unix_ms"]

    def test_nonzero_rank_writes_no_manifest(self, tmp_path):
        reg1 = hvd_metrics.MetricsRegistry(rank=1)
        w = _writer(tmp_path, reg1, rank=1)
        try:
            w.annotate(label="ignored")
        finally:
            w.close()
        assert hvd_history.load_manifest(str(tmp_path)) is None
        assert hvd_history.list_ranks(str(tmp_path)) in ([], [1])


class TestWriterDeath:
    def test_first_write_failure_kills_writer_not_run(self, tmp_path, reg):
        c = reg.counter("t_c")
        w = _writer(tmp_path, reg)
        shutil.rmtree(tmp_path)  # every segment open now fails
        c.inc()
        w.flush(wait=True)  # must swallow the failure
        assert w._dead
        assert w._m_err.value == 1
        kinds = [e["event"] for e in reg.snapshot()["events"]]
        assert "history_error" in kinds
        # Every later call is a cheap no-op — the run is unharmed.
        w.poke()
        w.flush(wait=True)
        assert w._m_err.value == 1
        w.close()

    def test_poke_respects_interval_deadline(self, tmp_path, reg):
        w = _writer(tmp_path, reg, interval_s=1000.0)
        try:
            w.poke(now=0.0)
            w.flush(wait=True)  # drain the first poke's record
            before = w._m_snaps.labels(kind="full").value + \
                w._m_snaps.labels(kind="delta").value
            for now in (1.0, 2.0, 999.0):
                w.poke(now=now)  # all before the next deadline
            w.flush(wait=True)
            reg.counter("t_bump").inc()
            w.poke(now=1001.0)  # past the deadline: schedules a record
            w.flush(wait=True)
        finally:
            w.close()
        records, _ = hvd_history.read_records(str(tmp_path), rank=0)
        assert records  # poke-driven records landed
        assert before >= 1


class TestModuleFacade:
    def test_reset_disabled_is_inert(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVD_HISTORY_DIR", str(tmp_path))
        try:
            w = hvd_history.reset(enabled=False)
            assert not w.enabled
            hvd_history.poke()
            hvd_history.flush(wait=True)
            assert list(tmp_path.glob("history-*.jsonl")) == []
        finally:
            hvd_history.reset(enabled=False)

    def test_reset_enabled_writes_under_dirpath(self, tmp_path):
        try:
            w = hvd_history.reset(enabled=True, dirpath=str(tmp_path),
                                  interval_s=3600.0)
            assert w.enabled and w.dir == str(tmp_path)
            hvd_history.flush(wait=True)
            records, torn = hvd_history.read_records(str(tmp_path),
                                                     rank=w.rank or 0)
            assert torn == 0 and records
        finally:
            hvd_history.reset(enabled=False)
