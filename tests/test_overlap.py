"""Overlap plane (docs/tensor-fusion.md): readiness-ordered bucket
dispatch inside the backward window, two-level hierarchical reduction
with the codec on the inter-host leg only, and bit-for-bit fp32 parity
with the barrier path.

Multi-process arms run through run.launch.run and skip on backends
whose XLA has no cross-process collectives (the CPU test platform) —
on a real pod they execute.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.run.launch import run
from horovod_tpu.utils import metrics as hvd_metrics

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
_CPU_MULTIPROC = "Multiprocess computations aren't implemented"


@pytest.fixture
def reg():
    """Fresh enabled registry; MUST precede hvd in test signatures so
    the coordinator binds its counters to it."""
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()


def _run2(fn, num_proc=2, env=None, **kw):
    try:
        return run(fn, num_proc=num_proc, env=env or _ENV, **kw)
    except RuntimeError as e:
        if _CPU_MULTIPROC in str(e):
            pytest.skip("XLA backend has no multiprocess collectives "
                        "(CPU test platform); runs on TPU/GPU pods")
        raise


def _quiet_background(coord):
    """Park the background flush loop on a long wait so the test's own
    flush_ready calls are the only dispatcher (hold_cycle can't be
    used: flush_ready honors the pause flag by design)."""
    coord._config.cycle_time_ms = 5000.0
    time.sleep(0.05)  # let the loop re-read the new period


class TestReadinessDispatch:
    def test_flush_ready_noop_when_disabled(self, reg, hvd):
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        _quiet_background(coord)
        h = hvd.allreduce_async(np.ones((8, 64), np.float32),
                                average=False, name="off.t0")
        coord.flush_ready()
        assert reg.counter("hvd_overlap_ready_flushes_total").value == 0
        hvd.synchronize(h)

    def test_flush_ready_drains_sealed_group_keeps_partial(self, reg,
                                                           hvd):
        """A fusion group whose queued bytes crossed the threshold is
        dispatched by flush_ready while a below-threshold group stays
        queued for the final drain — the seal detection that makes
        dispatch ride inside the backward window."""
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        cfg = coord._config
        cfg.overlap_eager = True
        cfg.fusion_threshold = 2048
        _quiet_background(coord)

        # different average flag -> different fusion group (same key
        # scheme as _make_plan), so "partial" really means a separate
        # group, not a member of the sealed one
        h_small = hvd.allreduce_async(np.ones((8, 4), np.float32),
                                      average=True, name="seal.small")
        coord.flush_ready()
        assert reg.counter("hvd_overlap_ready_flushes_total").value == 0

        big = np.arange(8.0 * 64, dtype=np.float32).reshape(8, 64)
        h_big = hvd.allreduce_async(big, average=False, name="seal.big")
        coord.flush_ready()
        assert reg.counter("hvd_overlap_ready_flushes_total").value == 1
        assert reg.counter("hvd_overlap_ready_tensors_total").value == 1

        out_big = np.asarray(hvd.synchronize(h_big))
        out_small = np.asarray(hvd.synchronize(h_small))
        np.testing.assert_allclose(
            out_big, np.tile(big.sum(0, keepdims=True), (8, 1)),
            rtol=1e-6)
        np.testing.assert_allclose(out_small, np.ones((8, 4)),
                                   rtol=1e-6)

    def test_reverse_order_enqueue_dispatches_before_final_drain(
            self, reg, hvd):
        """allreduce_gradients under HOROVOD_OVERLAP_EAGER enqueues in
        reverse tree order with flush_ready between enqueues: with two
        groups' worth of bytes, at least one ready drain must land
        BEFORE the whole-tree synchronize, and results come back in
        original leaf order."""
        import horovod_tpu
        from horovod_tpu import optim
        coord = horovod_tpu.common.state.global_state().coordinator
        cfg = coord._config
        cfg.overlap_eager = True
        cfg.fusion_threshold = 2048
        _quiet_background(coord)

        rng = np.random.RandomState(7)
        grads = {f"layer{i}": rng.randn(8, 64).astype(np.float32)
                 for i in range(4)}  # 2048 B each: every leaf seals
        out = optim.allreduce_gradients(grads, average=False)
        assert reg.counter("hvd_overlap_ready_flushes_total").value >= 1
        assert reg.counter("hvd_overlap_ready_tensors_total").value >= 1
        for k, g in grads.items():
            np.testing.assert_allclose(
                np.asarray(out[k]),
                np.tile(g.sum(0, keepdims=True), (8, 1)), rtol=1e-5)


class TestBitForBitParity:
    def _grads(self, seed):
        rng = np.random.RandomState(seed)
        return {f"l{i}": rng.randn(8, 48 + 16 * i).astype(np.float32)
                for i in range(5)}

    def test_fp32_overlap_matches_barrier_bitwise(self, reg, hvd):
        """Per-element psum is insensitive to bucket composition and
        dispatch order, so fp32 results must be IDENTICAL — not close —
        between the barrier path and readiness-ordered dispatch."""
        import horovod_tpu
        from horovod_tpu import optim
        coord = horovod_tpu.common.state.global_state().coordinator
        cfg = coord._config
        cfg.fusion_threshold = 4096
        grads = self._grads(11)

        cfg.overlap_eager = False
        barrier = jax.tree_util.tree_map(
            np.asarray, optim.allreduce_gradients(grads, average=True))
        cfg.overlap_eager = True
        _quiet_background(coord)
        overlap = jax.tree_util.tree_map(
            np.asarray, optim.allreduce_gradients(grads, average=True))

        for k in grads:
            assert barrier[k].dtype == overlap[k].dtype == np.float32
            assert np.array_equal(barrier[k], overlap[k]), k

    @pytest.mark.slow
    def test_fp32_parity_two_process(self):
        """Same bit-for-bit claim across real processes: each rank
        reduces the same pytree with overlap off then on; both must
        agree exactly on every rank."""
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu import optim
            from horovod_tpu.common import state

            hvd.init()
            cfg = state.global_state().config
            cfg.fusion_threshold = 4096
            rng = np.random.RandomState(3)
            grads = {f"l{i}": rng.randn(32 + 16 * i).astype(np.float32)
                     for i in range(4)}
            cfg.overlap_eager = False
            a = {k: np.asarray(v) for k, v in optim.allreduce_gradients(
                grads, average=True).items()}
            cfg.overlap_eager = True
            b = {k: np.asarray(v) for k, v in optim.allreduce_gradients(
                grads, average=True).items()}
            hvd.shutdown()
            return {k: bool(np.array_equal(a[k], b[k])) for k in grads}

        for res in _run2(fn):
            assert all(res.values()), res


class TestHierarchicalEngine:
    def test_invalid_local_size_raises(self, hvd):
        from horovod_tpu.ops.process_collectives import (
            HierarchicalProcessEngine)
        with pytest.raises(ValueError, match="divide"):
            HierarchicalProcessEngine(3)  # 1 % 3 != 0

    def test_trivial_world_quantized_matches_flat_math(self, hvd):
        """With one process the two-level schedule degenerates to the
        flat path's encode → sum → requant → decode — byte-for-byte the
        same kernels, so the results must agree exactly."""
        from horovod_tpu.ops import quantization as q
        from horovod_tpu.ops.process_collectives import (
            HierarchicalProcessEngine)
        eng = HierarchicalProcessEngine(1)
        rng = np.random.RandomState(5)
        x = rng.randn(600).astype(np.float32)
        block = 256
        full, comp, dec = eng.allreduce_quantized(
            jnp.asarray(x), "int8", block)
        flat, dec_flat = q.stacked_wire_allreduce(
            jnp.asarray(x)[None, :], block, "int8", False, 600)
        np.testing.assert_array_equal(np.asarray(full)[:600],
                                      np.asarray(flat)[0])
        # the EF shards it returns are the compensated input and its
        # own-wire decode
        np.testing.assert_array_equal(np.asarray(comp)[:600], x)
        np.testing.assert_array_equal(np.asarray(dec)[:600],
                                      np.asarray(dec_flat)[0])

    def test_hier_engine_ineligible_single_process(self, reg, hvd):
        """nproc==1 can never split: the coordinator property reports
        None and the quantized path stays flat."""
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        coord._config.overlap_hierarchical = True
        coord._config.overlap_local_size = 1
        assert coord._hier_engine is None

    def test_fingerprint_suffix_only_when_hierarchical(self, hvd):
        from horovod_tpu.common import state
        from horovod_tpu.ops import quantization as q
        cfg = state.global_state().config
        base = q.config_fingerprint(cfg)
        assert "/h" not in base
        cfg.overlap_hierarchical = True
        cfg.overlap_local_size = 4
        try:
            assert q.config_fingerprint(cfg) == base + "/h4"
        finally:
            cfg.overlap_hierarchical = False
            cfg.overlap_local_size = 0

    def test_account_leg_counters(self, reg):
        from horovod_tpu.ops import quantization as q
        q.account_leg("intra", None, 4096)
        q.account_leg("inter", "int8", 1040)
        fam = reg.counter("hvd_wire_leg_bytes_total",
                          labels=("leg", "codec"))
        assert fam.labels(leg="intra", codec="none").value == 4096
        assert fam.labels(leg="inter", codec="int8").value == 1040

    def test_error_feedback_peek(self, hvd):
        from horovod_tpu.ops import quantization as q
        ef = q.ErrorFeedback()
        assert ef.peek("k") is None
        comp = jnp.asarray(np.random.RandomState(0)
                           .randn(256).astype(np.float32))
        pl, sc = q.encode(comp, 256, "int8")
        ef.update("k", comp, q.decode(pl, sc, 256, 256), 256)
        assert ef.peek("k").shape == (256,)
        assert ef.peek("k", shape=(256,)) is not None
        assert ef.peek("k", shape=(512,)) is None

    @pytest.mark.slow
    def test_two_process_hierarchical_int8_inter_leg_only(self):
        """2 processes, local_size=1 (every process its own host): the
        fused eager allreduce rides the two-level engine, the int8
        codec crosses only the inter-host leg (wire-leg counters), and
        the sums are exact for values int8 blocks represent exactly."""
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common import state
            from horovod_tpu.utils import metrics as hvd_metrics

            hvd_metrics.reset(enabled=True)
            hvd.init()
            coord = state.global_state().coordinator
            r = hvd.rank()
            x = np.full((512,), float(r + 1), np.float32)
            out = np.asarray(hvd.allreduce(x, average=False,
                                           name="hier.t0"))
            eng = coord._hier_engine
            snap = hvd_metrics.get_registry().snapshot()["metrics"]
            legs = {tuple(sorted(v["labels"].items())): v["value"]
                    for v in snap.get("hvd_wire_leg_bytes_total",
                                      {}).get("values", [])}
            hvd.shutdown()
            return dict(
                ok=bool(np.allclose(out, 3.0)),
                hier=eng is not None,
                legs={str(k): v for k, v in legs.items()})

        # knobs go in via env so every rank NEGOTIATES the same wire
        # fingerprint from init (mutating config after init trips the
        # MismatchError guard by design)
        env = dict(_ENV)
        env["HOROVOD_COMPRESSION"] = "int8"
        env["HOROVOD_QUANT_MIN_BYTES"] = "0"
        env["HOROVOD_OVERLAP_HIERARCHICAL"] = "1"
        env["HOROVOD_OVERLAP_LOCAL_SIZE"] = "1"
        for res in _run2(fn, env=env):
            assert res["ok"] and res["hier"], res
            inter_int8 = [v for k, v in res["legs"].items()
                          if "inter" in k and "int8" in k]
            intra_int8 = [v for k, v in res["legs"].items()
                          if "intra" in k and "int8" in k]
            assert inter_int8 and inter_int8[0] > 0, res
            assert not intra_int8, res


class TestChaosDelayedInterHostLeg:
    @pytest.mark.slow
    def test_delayed_negotiation_leg_still_completes(self):
        """Chaos-delay the negotiated control plane under overlap +
        hierarchy: the retry/stall machinery must absorb the late leg
        and every collective still completes with exact sums."""
        def fn():
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.rank()
            outs = []
            for i in range(3):
                x = np.full((64,), float((r + 1) * (i + 1)), np.float32)
                outs.append(float(np.asarray(hvd.allreduce(
                    x, average=False, name=f"chaos.t{i}"))[0]))
            hvd.shutdown()
            return outs

        env = dict(_ENV)
        env["HOROVOD_OVERLAP_EAGER"] = "1"
        env["HOROVOD_OVERLAP_HIERARCHICAL"] = "1"
        env["HOROVOD_OVERLAP_LOCAL_SIZE"] = "1"
        env["HVD_CHAOS_SPEC"] = "negotiation:*:delay_response:0.5"
        env["HVD_CHAOS_DELAY_MS"] = "120"
        env["HVD_CHAOS_SEED"] = "17"
        for res in _run2(fn, env=env, start_timeout_s=300.0):
            assert res == [3.0 * (i + 1) for i in range(3)], res
