"""Alerting plane (utils/alerts.py): burn-rate math, the shared
RollingWindow container, RuleView windowed lookups, the
pending -> firing -> resolved state machine with two-sided hysteresis,
one-shot escalation (flight dump + incident file), and the default
rule pack evaluated against synthetic registry traffic on a virtual
clock — no sleeps, no real time.
"""

import json

import pytest

from horovod_tpu.utils import alerts as hvd_alerts
from horovod_tpu.utils import history as hvd_history
from horovod_tpu.utils import metrics as hvd_metrics


@pytest.fixture
def reg():
    """Standalone registry; tests never touch the process singleton."""
    return hvd_metrics.MetricsRegistry(rank=0)


def _manager(reg, rules, tmp_path, **kw):
    kw.setdefault("interval_s", 0.0)
    kw.setdefault("incident_dir", str(tmp_path))
    kw.setdefault("history_writer", hvd_history.NullHistoryWriter())
    return hvd_alerts.AlertManager(registry=reg, rules=rules, **kw)


def _no_dump(monkeypatch):
    """Keep escalation hermetic: capture flight-dump reasons instead of
    writing real dumps."""
    reasons = []
    monkeypatch.setattr("horovod_tpu.utils.tracing.dump_on_failure",
                        reasons.append)
    return reasons


class _Acc:
    """Minimal accumulator for RollingWindow (observe + n)."""

    def __init__(self):
        self.vals = []

    def observe(self, v):
        self.vals.append(v)

    @property
    def n(self):
        return len(self.vals)


class TestBurnRate:
    def test_empty_window_is_zero(self):
        assert hvd_alerts.burn_rate(0, 0, 0.9) == 0.0
        assert hvd_alerts.burn_rate(100, 0, 0.9) == 0.0

    def test_burn_one_at_the_slo_boundary(self):
        # 10% bad against a 0.9 target spends the budget exactly.
        assert hvd_alerts.burn_rate(90, 10, 0.9) == pytest.approx(1.0)
        assert hvd_alerts.burn_rate(80, 20, 0.9) == pytest.approx(2.0)

    def test_no_budget_means_infinite_burn(self):
        assert hvd_alerts.burn_rate(99, 1, 1.0) == float("inf")


class TestRollingWindow:
    def test_rollover_retains_last_full(self):
        w = hvd_alerts.RollingWindow(3, _Acc)
        for v in (1, 2, 3):
            w.observe(v)
        assert w.last_full.vals == [1, 2, 3]
        assert w.current.n == 0
        w.observe(4)
        assert w.recent().vals == [4]  # rolling wins once non-empty

    def test_recent_falls_back_to_last_full(self):
        w = hvd_alerts.RollingWindow(2, _Acc)
        w.observe(1)
        w.observe(2)
        assert w.recent().vals == [1, 2]

    def test_freeze_prefers_last_full_when_rolling_thin(self):
        w = hvd_alerts.RollingWindow(4, _Acc)
        for v in (1, 2, 3, 4):
            w.observe(v)
        w.observe(5)  # rolling has 1 < size//2 samples
        base = w.freeze()
        assert base.vals == [1, 2, 3, 4]
        # rolling restarted either way
        assert w.current.n == 0
        # the last-full is retained so recent() still has history
        assert w.recent() is not None

    def test_freeze_uses_rolling_when_thick_enough(self):
        w = hvd_alerts.RollingWindow(4, _Acc)
        for v in (1, 2, 3, 4, 5, 6):
            w.observe(v)
        base = w.freeze()
        assert base.vals == [5, 6]


class TestRuleView:
    def _view(self, reg, samplers=None, now=100.0):
        return hvd_alerts.RuleView(reg.snapshot(max_events=0),
                                   samplers or {}, now)

    def test_value_sums_children_and_filters_labels(self, reg):
        fam = reg.counter("t_ops", labels=("op",))
        fam.labels(op="a").inc(3)
        fam.labels(op="b").inc(4)
        view = self._view(reg)
        assert view.value("t_ops") == 7.0
        assert view.value("t_ops", labels={"op": "a"}) == 3.0
        assert view.value("t_missing", default=-1.0) == -1.0
        assert view.has("t_ops") and not view.has("t_missing")

    def test_delta_is_windowed_and_clamped(self, reg):
        c = reg.counter("t_c")
        c.inc(10)
        sampler = hvd_alerts._Sampler()
        sampler.add(40.0, 2.0)
        sampler.add(65.0, 6.0)
        samplers = {("v", "t_c", hvd_alerts._labels_key(None)): sampler}
        view = self._view(reg, samplers, now=100.0)
        # window start 70 -> cumulative-at-start is the t=65 sample
        assert view.delta("t_c", 30.0) == 4.0   # 10 - 6
        # window start 30 predates every sample -> oldest retained
        assert view.delta("t_c", 70.0) == 8.0   # 10 - 2
        # no sampler yet: whole lifetime is the window
        view2 = self._view(reg, {}, now=100.0)
        assert view2.delta("t_c", 30.0) == 10.0

    def test_windowed_quantile_uses_count_deltas(self, reg):
        h = reg.histogram("t_lat", buckets=(0.1, 1.0, 10.0))
        for _ in range(50):
            h.labels().observe(0.05)  # old fast traffic
        snap_counts = [0] * 4
        for v in reg.snapshot()["metrics"]["t_lat"]["values"]:
            for i, c in enumerate(v["counts"]):
                snap_counts[i] += c
        sampler = hvd_alerts._Sampler()
        sampler.add(50.0, snap_counts)
        for _ in range(10):
            h.labels().observe(5.0)   # recent slow traffic
        samplers = {("h", "t_lat"): sampler}
        view = self._view(reg, samplers, now=100.0)
        # cumulative p50 dominated by the fast traffic
        assert view.quantile("t_lat", 0.5) <= 0.1
        # windowed p50 sees only the slow tail
        assert view.quantile("t_lat", 0.5, window_s=30.0) > 1.0
        assert view.window_count("t_lat", 30.0) == 10
        assert view.quantile("t_missing", 0.5) is None


class TestLifecycle:
    def _rule(self, breach_box, **kw):
        kw.setdefault("for_s", 5.0)
        return hvd_alerts.Rule(
            "t_rule", lambda view: (breach_box[0], {"v": 1}), **kw)

    def test_pending_fires_after_for_duration(self, reg, tmp_path,
                                              monkeypatch):
        _no_dump(monkeypatch)
        breach = [True]
        mgr = _manager(reg, [self._rule(breach)], tmp_path)
        mgr.tick(now=0.0)
        assert mgr.states()["t_rule"]["state"] == "pending"
        mgr.tick(now=3.0)
        assert mgr.firing() == []       # held < for_s
        mgr.tick(now=5.0)
        assert mgr.firing() == ["t_rule"]
        assert mgr.states()["t_rule"]["evidence"] == {"v": 1}

    def test_blip_is_cancelled_not_fired(self, reg, tmp_path, monkeypatch):
        _no_dump(monkeypatch)
        breach = [True]
        mgr = _manager(reg, [self._rule(breach)], tmp_path)
        mgr.tick(now=0.0)
        breach[0] = False
        mgr.tick(now=2.0)
        assert mgr.states()["t_rule"]["state"] == "inactive"
        kinds = [e["event"] for e in reg.events()]
        assert "alert_cancelled" in kinds and "alert_firing" not in kinds

    def test_resolve_needs_clear_hold(self, reg, tmp_path, monkeypatch):
        _no_dump(monkeypatch)
        breach = [True]
        mgr = _manager(reg, [self._rule(breach, for_s=0.0, clear_s=10.0)],
                       tmp_path)
        mgr.tick(now=0.0)   # zero for-duration fires on the same tick
        assert mgr.firing() == ["t_rule"]
        breach[0] = False
        mgr.tick(now=1.0)
        mgr.tick(now=5.0)
        assert mgr.firing() == ["t_rule"]   # clear streak < clear_s
        breach[0] = True
        mgr.tick(now=6.0)   # re-breach resets the clear streak
        breach[0] = False
        mgr.tick(now=7.0)
        mgr.tick(now=12.0)
        assert mgr.firing() == ["t_rule"]   # streak restarted at 7
        mgr.tick(now=17.0)
        assert mgr.firing() == []
        kinds = [e["event"] for e in reg.events()]
        assert kinds.count("alert_firing") == 1
        assert kinds.count("alert_resolved") == 1

    def test_escalation_is_one_shot_per_episode(self, reg, tmp_path,
                                                monkeypatch):
        reasons = _no_dump(monkeypatch)
        breach = [True]
        mgr = _manager(reg, [self._rule(breach, for_s=0.0, clear_s=1.0)],
                       tmp_path)
        mgr.tick(now=0.0)
        mgr.tick(now=1.0)   # still firing: no second dump
        assert reasons == ["alert:t_rule"]
        assert len(mgr.incidents) == 1
        breach[0] = False
        mgr.tick(now=2.0)
        mgr.tick(now=4.0)   # resolved
        breach[0] = True
        mgr.tick(now=5.0)   # new episode fires again
        assert reasons == ["alert:t_rule", "alert:t_rule"]
        assert len(mgr.incidents) == 2

    def test_state_gauge_and_transition_counters(self, reg, tmp_path,
                                                 monkeypatch):
        _no_dump(monkeypatch)
        breach = [True]
        mgr = _manager(reg, [self._rule(breach, for_s=5.0)], tmp_path)
        mgr.tick(now=0.0)
        snap = reg.snapshot(max_events=0)["metrics"]
        assert snap["hvd_alert_state"]["values"][0]["value"] == 1.0
        mgr.tick(now=5.0)
        snap = reg.snapshot(max_events=0)["metrics"]
        assert snap["hvd_alert_state"]["values"][0]["value"] == 2.0
        trans = {tuple(sorted(v["labels"].items())): v["value"]
                 for v in snap["hvd_alerts_total"]["values"]}
        assert trans[(("alert", "t_rule"), ("transition", "pending"))] == 1
        assert trans[(("alert", "t_rule"), ("transition", "firing"))] == 1

    def test_broken_predicate_is_isolated(self, reg, tmp_path, monkeypatch):
        _no_dump(monkeypatch)

        def boom(view):
            raise RuntimeError("predicate bug")

        breach = [True]
        rules = [hvd_alerts.Rule("t_boom", boom, for_s=0.0),
                 self._rule(breach, for_s=0.0)]
        mgr = _manager(reg, rules, tmp_path)
        mgr.tick(now=0.0)   # must not raise; healthy rule still fires
        assert mgr.firing() == ["t_rule"]
        assert mgr.states()["t_boom"]["state"] == "inactive"

    def test_interval_gates_evaluation(self, reg, tmp_path, monkeypatch):
        _no_dump(monkeypatch)
        breach = [True]
        mgr = _manager(reg, [self._rule(breach, for_s=0.0)], tmp_path,
                       interval_s=10.0)
        mgr.tick(now=0.0)
        assert mgr.firing() == ["t_rule"]
        breach[0] = False
        mgr.tick(now=5.0)   # before the deadline: not evaluated
        mgr.tick(now=9.9)
        assert mgr.firing() == ["t_rule"]


class TestIncidentCapture:
    def test_incident_bundles_history_events_and_stranded_ids(
            self, reg, tmp_path, monkeypatch):
        _no_dump(monkeypatch)
        writer = hvd_history.HistoryWriter(
            str(tmp_path), registry=reg, interval_s=3600.0)
        try:
            reg.event("serve_admit", request_id="req-1")
            reg.event("serve_admit", request_id="req-2")
            reg.event("serve_retire", request_id="req-1",
                      phase_ms={"prefill": 30.0, "decode": 120.0},
                      trace_id="tr-9")
            writer.flush(wait=True)
            breach = [True]
            rule = hvd_alerts.Rule(
                "t_inc", lambda view: (breach[0], {"why": "drill"}),
                for_s=0.0, severity="page")
            mgr = _manager(reg, [rule], tmp_path, history_writer=writer)
            mgr.tick(now=0.0)
        finally:
            writer.close()
        assert len(mgr.incidents) == 1
        with open(mgr.incidents[0]) as f:
            inc = json.load(f)
        assert inc["alert"] == "t_inc"
        assert inc["severity"] == "page"
        assert inc["evidence"] == {"why": "drill"}
        assert inc["stranded_request_ids"] == ["req-2"]
        assert inc["dominant_phase"] == "decode"
        assert "tr-9" in inc["trace_ids"]
        assert inc["history"], "WAL slice must ride the incident"
        assert inc["manifest"] is not None
        kinds = [e["event"] for e in reg.events()]
        assert "alert_incident" in kinds
        # Incident counter bumped for this alert.
        snap = reg.snapshot(max_events=0)["metrics"]
        assert snap["hvd_incidents_total"]["values"][0]["value"] == 1.0


class TestDefaultPack:
    def test_goodput_burn_needs_both_windows_hot(self, reg, tmp_path,
                                                 monkeypatch):
        _no_dump(monkeypatch)
        monkeypatch.setenv("HVD_ALERT_FOR_S", "5.0")
        good = reg.counter("hvd_serve_goodput_tokens_total")
        bad = reg.counter("hvd_serve_wasted_tokens_total")
        rules = [r for r in hvd_alerts.default_rules()
                 if r.name == "serve_goodput_burn"]
        mgr = _manager(reg, rules, tmp_path)
        # 100s of healthy traffic: burn stays cold.
        now = 0.0
        for _ in range(100):
            good.inc(100)
            mgr.tick(now=now)
            now += 1.0
        assert mgr.states()["serve_goodput_burn"]["state"] == "inactive"
        # Waste spikes to 50% (5x burn at the 0.9 SLO). The short
        # window goes hot almost immediately; the long one needs the
        # damage to accrue against the healthy tail -> material spend.
        for _ in range(40):
            good.inc(50)
            bad.inc(50)
            mgr.tick(now=now)
            now += 1.0
        assert mgr.firing() == ["serve_goodput_burn"]
        ev = mgr.states()["serve_goodput_burn"]["evidence"]
        assert ev["burn_15s"] >= 2.0 and ev["burn_60s"] >= 2.0
        # Load drops: the long window stays hot a while, but the short
        # window cooling is enough to stop the breach -> resolves.
        for _ in range(40):
            good.inc(100)
            mgr.tick(now=now)
            now += 1.0
        assert mgr.firing() == []

    def test_ttft_rule_needs_min_volume(self, reg, tmp_path, monkeypatch):
        _no_dump(monkeypatch)
        h = reg.histogram("hvd_serve_ttft_seconds",
                          buckets=(0.5, 1.0, 2.0, 4.0))
        rules = [r for r in hvd_alerts.default_rules()
                 if r.name == "serve_ttft_p99"]
        mgr = _manager(reg, rules, tmp_path)
        for _ in range(3):
            h.labels().observe(3.5)   # slow but under min volume
        mgr.tick(now=0.0)
        assert mgr.states()["serve_ttft_p99"]["state"] == "inactive"
        for _ in range(10):
            h.labels().observe(3.5)
        mgr.tick(now=1.0)
        assert mgr.states()["serve_ttft_p99"]["state"] == "pending"

    def test_stall_and_hbm_rules_read_gauges(self, reg, tmp_path,
                                             monkeypatch):
        _no_dump(monkeypatch)
        rules = [r for r in hvd_alerts.default_rules()
                 if r.name in ("stall", "hbm_headroom_low")]
        mgr = _manager(reg, rules, tmp_path)
        mgr.tick(now=0.0)
        states = mgr.states()
        assert states["stall"]["state"] == "inactive"
        assert states["hbm_headroom_low"]["state"] == "inactive"
        reg.gauge("hvd_stalled_ranks").set(2)
        reg.gauge("hvd_hbm_capacity_bytes").set(16e9)
        reg.gauge("hvd_hbm_headroom_bytes").set(0.5e9)  # 3% headroom
        mgr.tick(now=1.0)
        states = mgr.states()
        assert states["stall"]["state"] == "pending"
        assert states["hbm_headroom_low"]["state"] == "pending"

    def test_pack_names_are_stable(self):
        names = [r.name for r in hvd_alerts.default_rules()]
        assert names == ["serve_goodput_burn", "serve_ttft_p99",
                         "hbm_headroom_low", "recompile_storm", "stall",
                         "nonfinite_burst", "breaker_flap"]


class TestModuleFacade:
    def test_reset_disabled_is_inert(self):
        try:
            mgr = hvd_alerts.reset(enabled=False)
            assert not mgr.enabled
            hvd_alerts.tick()
            assert mgr.firing() == [] and mgr.states() == {}
        finally:
            hvd_alerts.reset(enabled=False)
