"""Cross-process consistency checking (the coordinator's ConstructResponse
error checks, operations.cc:209-371): ranks submitting mismatched
shapes/dtypes to the same named collective must get a MismatchError naming
the tensor, not a transport hang/crash. Workers are spawned via the
programmatic run(fn) launcher (test_spark.py-style, closures shipped by
cloudpickle)."""

from horovod_tpu.run.launch import run

# NOTE: worker closures must not reference this module's globals —
# cloudpickle would serialize them by reference and the spawned workers
# cannot import the test module. The CPU-platform env rides run(env=...)
# because the container's sitecustomize imports jax at interpreter start,
# before fn runs.
_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


class TestCrossProcessConsistency:
    def test_matching_allreduce_succeeds(self):
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            out = hvd.allreduce(np.ones((2, 3), np.float32), average=False)
            hvd.shutdown()
            return float(np.asarray(out)[0, 0])

        assert run(fn, num_proc=2, env=_ENV) == [2.0, 2.0]

    def test_shape_mismatch_raises_named_error(self):
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            rank = int(os.environ["HVD_PROCESS_ID"])
            # rank-dependent shape — the reference's error-path test
            # pattern (test_torch.py rank-dependent dims)
            shape = (2, 3) if rank == 0 else (2, 4)
            try:
                hvd.allreduce(np.ones(shape, np.float32), name="bad.shape")
                return "no error"
            except hvd.MismatchError as e:
                return f"mismatch:{('bad.shape' in str(e))}"
            finally:
                hvd.shutdown()

        assert run(fn, num_proc=2, env=_ENV) == ["mismatch:True", "mismatch:True"]

    def test_dtype_mismatch_raises(self):
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            rank = int(os.environ["HVD_PROCESS_ID"])
            dtype = np.float32 if rank == 0 else np.int32
            try:
                hvd.allreduce(np.ones((2, 2), dtype), name="bad.dtype")
                return "no error"
            except hvd.MismatchError:
                return "mismatch"
            finally:
                hvd.shutdown()

        assert run(fn, num_proc=2, env=_ENV) == ["mismatch", "mismatch"]

    def test_reducescatter_and_alltoall_cross_process(self):
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            # reducescatter: both submit [4] vectors; each keeps its half
            rs = np.asarray(hvd.reducescatter(
                np.full((4,), r + 1.0, np.float32)))
            # alltoall: rank r sends [10r, 10r+1]; rank i receives
            # [10*0+i, 10*1+i]
            a2a = np.asarray(hvd.alltoall(
                np.asarray([10.0 * r, 10.0 * r + 1], np.float32)))
            hvd.shutdown()
            return (rs.tolist(), a2a.tolist())

        out = run(fn, num_proc=2, env=_ENV)
        assert out[0] == ([3.0, 3.0], [0.0, 10.0])
        assert out[1] == ([3.0, 3.0], [1.0, 11.0])

    def test_allgather_first_dim_may_differ(self):
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            rank = int(os.environ["HVD_PROCESS_ID"])
            x = np.full((rank + 1, 2), float(rank), np.float32)
            out = np.asarray(hvd.allgather(x))
            hvd.shutdown()
            return out.shape[0]

        # variable-first-dim allgatherv (MPIAllgather parity)
        assert run(fn, num_proc=2, env=_ENV) == [3, 3]
