"""MXNet frontend: collectives on NDArrays, DistributedOptimizer update
path, gluon DistributedTrainer grad exchange, broadcast_parameters with
deferred init (reference test_mxnet.py patterns — single-process here, so
process-level collectives are identity; the NDArray bridge, rescale_grad
normalization and init hooks are what's under test).

mxnet is not in the image, so a minimal numpy-backed stand-in is
registered as ``mxnet`` — the frontend only relies on the NDArray duck
type (asnumpy/__setitem__/dtype/wait_to_read, optional context) and the
Optimizer/Trainer base-class contracts exercised below.
"""

import sys
import types as _types

import numpy as np
import pytest


def _install_fake_mxnet():
    if "mxnet" in sys.modules:
        return sys.modules["mxnet"]

    class NDArray:
        def __init__(self, data, ctx="cpu(0)", dtype=None):
            self._data = np.array(data, dtype=dtype)
            self.context = ctx

        def asnumpy(self):
            return self._data

        def __setitem__(self, key, value):
            self._data[key] = value

        @property
        def shape(self):
            return self._data.shape

        @property
        def dtype(self):
            return self._data.dtype

        def wait_to_read(self):
            pass

    nd = _types.ModuleType("mxnet.nd")
    nd.NDArray = NDArray
    nd.array = lambda data, ctx="cpu(0)", dtype=None: NDArray(
        data, ctx=ctx, dtype=dtype)
    nd.zeros = lambda shape, ctx="cpu(0)", dtype=None: NDArray(
        np.zeros(shape), ctx=ctx, dtype=dtype)

    class Optimizer:
        def __init__(self, learning_rate=0.1):
            self.lr = learning_rate
            self.rescale_grad = 1.0

        def update(self, index, weight, grad, state):
            weight[:] = (weight.asnumpy()
                         - self.lr * self.rescale_grad * grad.asnumpy())

        def update_multi_precision(self, index, weight, grad, state):
            self.update(index, weight, grad, state)

        def create_state_multi_precision(self, index, weight):
            return None

        def set_learning_rate(self, lr):
            self.lr = lr

    optimizer = _types.ModuleType("mxnet.optimizer")
    optimizer.Optimizer = Optimizer

    class DeferredInitializationError(Exception):
        pass

    class Parameter:
        def __init__(self, data=None, grad=None, grad_req="write"):
            self._data = data
            self._grad = grad
            self.grad_req = grad_req

        def data(self):
            if self._data is None:
                raise DeferredInitializationError()
            return self._data

        def list_grad(self):
            return [self._grad]

        def _init_impl(self, data):
            self._data = data

    class Trainer:
        def __init__(self, params, optimizer, optimizer_params=None,
                     kvstore=None):
            self._params = list(params.values()) if hasattr(params, "values") \
                else list(params)
            self._scale = 1.0
            self._optimizer = optimizer

        def step(self, batch_size):
            self._allreduce_grads()

    class ParameterDict(dict):
        pass

    parameter = _types.ModuleType("mxnet.gluon.parameter")
    parameter.DeferredInitializationError = DeferredInitializationError
    parameter.Parameter = Parameter
    parameter.ParameterDict = ParameterDict

    gluon = _types.ModuleType("mxnet.gluon")
    gluon.Trainer = Trainer
    gluon.parameter = parameter

    mx = _types.ModuleType("mxnet")
    mx.nd = nd
    mx.optimizer = optimizer
    mx.gluon = gluon
    sys.modules["mxnet"] = mx
    sys.modules["mxnet.nd"] = nd
    sys.modules["mxnet.optimizer"] = optimizer
    sys.modules["mxnet.gluon"] = gluon
    sys.modules["mxnet.gluon.parameter"] = parameter
    return mx


@pytest.fixture
def mx():
    return _install_fake_mxnet()


@pytest.fixture
def mhvd(hvd, mx):
    import horovod_tpu.mxnet as mhvd_mod
    return mhvd_mod


class TestMXNetOps:
    def test_allreduce_identity_single_process(self, mx, mhvd):
        x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
        out = mhvd.allreduce(x, average=True)
        assert out is not x
        np.testing.assert_allclose(out.asnumpy(), x.asnumpy())

    def test_allreduce_inplace(self, mx, mhvd):
        x = mx.nd.array(3 * np.ones(4, np.float32))
        out = mhvd.allreduce_(x, average=False)
        assert out is x
        np.testing.assert_allclose(x.asnumpy(), 3 * np.ones(4))

    def test_grouped_allreduce_buckets_and_splits_back(self, mx, mhvd):
        # mixed shapes + dtypes: buckets are dtype-homogeneous, results
        # must land back in the right tensors with their original shapes
        xs = [mx.nd.array(np.full((2, 3), 1.0, np.float32)),
              mx.nd.array(np.full(5, 2.0, np.float32)),
              mx.nd.array(np.full(4, 3.0, np.float64)),
              mx.nd.array(np.full((3, 1), 4.0, np.float32))]
        out = mhvd.grouped_allreduce_(xs, average=False, name="g",
                                      priority=-1)
        assert out is xs
        np.testing.assert_allclose(xs[0].asnumpy(), np.full((2, 3), 1.0))
        np.testing.assert_allclose(xs[1].asnumpy(), np.full(5, 2.0))
        np.testing.assert_allclose(xs[2].asnumpy(), np.full(4, 3.0))
        assert xs[2].dtype == np.float64
        np.testing.assert_allclose(xs[3].asnumpy(), np.full((3, 1), 4.0))

    def test_grouped_allreduce_respects_zero_threshold(self, mx, mhvd,
                                                       monkeypatch):
        from horovod_tpu.common import state as state_mod
        monkeypatch.setattr(state_mod.global_state().config,
                            "fusion_threshold", 0)
        xs = [mx.nd.array(np.full(3, float(i))) for i in range(3)]
        mhvd.grouped_allreduce_(xs, average=True)
        for i, x in enumerate(xs):
            np.testing.assert_allclose(x.asnumpy(), np.full(3, float(i)))

    def test_allgather(self, mx, mhvd):
        x = mx.nd.array(np.arange(4, dtype=np.float32).reshape(2, 2))
        out = mhvd.allgather(x)
        assert out.shape[0] == 2 * mhvd.process_count()

    def test_broadcast_inplace(self, mx, mhvd):
        x = mx.nd.array(np.random.RandomState(0).randn(5))
        want = x.asnumpy().copy()
        out = mhvd.broadcast_(x, root_rank=0)
        assert out is x
        np.testing.assert_allclose(x.asnumpy(), want)

    def test_rejects_non_ndarray(self, mhvd):
        with pytest.raises(ValueError, match="NDArray"):
            mhvd.allreduce(np.ones(3))

    def test_size_rank_are_process_level(self, mhvd):
        assert mhvd.size() == mhvd.process_count()
        assert mhvd.rank() == mhvd.process_rank()


class TestDistributedOptimizer:
    def test_rescale_grad_normalized(self, mx, mhvd):
        opt = mx.optimizer.Optimizer()
        dopt = mhvd.DistributedOptimizer(opt)
        assert opt.rescale_grad == pytest.approx(1.0 / mhvd.size())
        assert dopt.lr == opt.lr  # __getattr__ passthrough

    def test_update_allreduces_then_updates(self, mx, mhvd):
        opt = mx.optimizer.Optimizer(learning_rate=0.5)
        dopt = mhvd.DistributedOptimizer(opt)
        w = mx.nd.array(np.ones(3, np.float32))
        g = mx.nd.array(2 * np.ones(3, np.float32))
        dopt.update(0, w, g, None)
        # single process: sum == identity; w -= lr * rescale * g
        np.testing.assert_allclose(
            w.asnumpy(), 1.0 - 0.5 * (1.0 / mhvd.size()) * 2.0)

    def test_update_list_index_allreduces_each(self, mx, mhvd):
        dopt = mhvd.DistributedOptimizer(mx.optimizer.Optimizer())
        gs = [mx.nd.array(np.full(2, i + 1, np.float32)) for i in range(2)]
        dopt._do_allreduce([10, 11], gs)
        for i, g in enumerate(gs):
            np.testing.assert_allclose(g.asnumpy(), np.full(2, i + 1))


class TestDistributedTrainer:
    def test_allreduce_grads_and_scale(self, mx, mhvd):
        P = sys.modules["mxnet.gluon.parameter"].Parameter
        params = {f"p{i}": P(data=mx.nd.array(np.ones(2)),
                             grad=mx.nd.array(np.full(2, float(i))))
                  for i in range(3)}
        params["frozen"] = P(grad_req="null")
        tr = mhvd.DistributedTrainer(params, mx.optimizer.Optimizer())
        assert tr._scale == pytest.approx(1.0 / mhvd.size())
        tr.step(1)
        for i in range(3):
            np.testing.assert_allclose(
                params[f"p{i}"].list_grad()[0].asnumpy(), float(i))

    def test_unwraps_distributed_optimizer(self, mx, mhvd):
        inner = mx.optimizer.Optimizer()
        with pytest.warns(UserWarning, match="unwrapped"):
            tr = mhvd.DistributedTrainer({}, mhvd.DistributedOptimizer(inner))
        assert tr._optimizer is inner


class TestBroadcastParameters:
    def test_dict_of_ndarrays(self, mx, mhvd):
        params = {"a": mx.nd.array(np.ones(3)),
                  "b": mx.nd.array(np.zeros(2))}
        mhvd.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(params["a"].asnumpy(), np.ones(3))

    def test_deferred_init_hooked(self, mx, mhvd):
        P = sys.modules["mxnet.gluon.parameter"].Parameter
        PD = mx.gluon.parameter.ParameterDict

        ready = P(data=mx.nd.array(np.ones(2)))
        deferred = P()  # no data yet -> DeferredInitializationError
        params = PD(ready=ready, deferred=deferred)
        mhvd.broadcast_parameters(params, root_rank=0)
        # initializing the deferred param triggers the injected broadcast
        deferred._init_impl(mx.nd.array(np.full(2, 7.0)))
        np.testing.assert_allclose(deferred.data().asnumpy(), np.full(2, 7.0))

    def test_plain_dict_of_parameters_mxnet2_style(self, mx, mhvd):
        # MXNet 2.x collect_params() returns dict[str, Parameter]
        P = sys.modules["mxnet.gluon.parameter"].Parameter
        ready = P(data=mx.nd.array(np.full(2, 3.0)))
        deferred = P()
        params = {"ready": ready, "deferred": deferred}
        mhvd.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(ready.data().asnumpy(), np.full(2, 3.0))
        deferred._init_impl(mx.nd.array(np.full(2, 9.0)))
        np.testing.assert_allclose(deferred.data().asnumpy(), np.full(2, 9.0))

    def test_invalid_params_type(self, mhvd):
        with pytest.raises(ValueError, match="invalid params"):
            mhvd.broadcast_parameters([1, 2, 3])
