"""TpuBatchNorm (ops/batch_norm.py): the Pallas-fused BN statistics must
be a numerical drop-in for flax.linen.BatchNorm — forward, backward
(dx/dscale/dbias through the custom VJP), running-stats update, and eval
mode — so models/resnet.py's norm_impl="tpu" path stays selectable (the
default is "flax": the Pallas route measured slower on v5e, see
ops/batch_norm.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from horovod_tpu.ops import batch_norm as bn


@pytest.fixture
def x():
    return jnp.asarray(
        np.random.RandomState(0).randn(4, 5, 5, 24) * 2.0 + 0.5,
        jnp.float32)


class TestMoments:
    def test_moments_match_numpy(self, x):
        s, ss = bn.moments(x)
        xf = np.asarray(x, np.float64).reshape(-1, x.shape[-1])
        np.testing.assert_allclose(np.asarray(s), xf.sum(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ss), (xf * xf).sum(0),
                                   rtol=1e-5)

    def test_moments2_match_numpy(self, x):
        y = x * 0.3 - 1.0
        sa, sab = bn.moments2(y, x)
        xf = np.asarray(x, np.float64).reshape(-1, x.shape[-1])
        yf = np.asarray(y, np.float64).reshape(-1, x.shape[-1])
        np.testing.assert_allclose(np.asarray(sa), yf.sum(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(sab), (yf * xf).sum(0),
                                   rtol=1e-5)

    def test_odd_row_count_single_block(self):
        x = jnp.ones((7, 3, 24))  # 21 rows: not a multiple of 8
        s, ss = bn.moments(x)
        np.testing.assert_allclose(np.asarray(s), 21.0)


class TestAgainstFlax:
    def _pair(self, momentum=0.9):
        tpu = bn.TpuBatchNorm(use_running_average=False, momentum=momentum,
                              epsilon=1e-5)
        ref = nn.BatchNorm(use_running_average=False, momentum=momentum,
                           epsilon=1e-5)
        return tpu, ref

    def test_forward_and_running_stats(self, x):
        tpu, ref = self._pair()
        vt = tpu.init(jax.random.PRNGKey(0), x)
        vr = ref.init(jax.random.PRNGKey(0), x)
        yt, st = tpu.apply(vt, x, mutable=["batch_stats"])
        yr, sr = ref.apply(vr, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(yt), np.asarray(yr),
                                   atol=2e-5)
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(st["batch_stats"][k]),
                np.asarray(sr["batch_stats"][k]), atol=2e-5)

    def test_backward_matches(self, x):
        tpu, ref = self._pair()
        vt = tpu.init(jax.random.PRNGKey(0), x)
        vr = ref.init(jax.random.PRNGKey(0), x)

        def loss(variables, mod, x):
            y, _ = mod.apply(variables, x, mutable=["batch_stats"])
            return jnp.sum(y ** 2 + 0.3 * y)

        gt = jax.grad(loss)(vt, tpu, x)
        gr = jax.grad(loss)(vr, ref, x)
        np.testing.assert_allclose(
            np.asarray(gt["params"]["scale"]),
            np.asarray(gr["params"]["scale"]), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(gt["params"]["bias"]),
            np.asarray(gr["params"]["bias"]), rtol=2e-4, atol=2e-4)

        gx_t = jax.grad(lambda x: loss(vt, tpu, x))(x)
        gx_r = jax.grad(lambda x: loss(vr, ref, x))(x)
        np.testing.assert_allclose(np.asarray(gx_t), np.asarray(gx_r),
                                   rtol=2e-4, atol=2e-4)

    def test_eval_mode_uses_running_stats(self, x):
        tpu, _ = self._pair()
        variables = tpu.init(jax.random.PRNGKey(0), x)
        _, upd = tpu.apply(variables, x, mutable=["batch_stats"])
        variables = {**variables, **upd}
        eval_mod = bn.TpuBatchNorm(use_running_average=True)
        y1 = eval_mod.apply(variables, x)
        y2 = eval_mod.apply(variables, x * 0 + x)  # same input, no stats dep
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))
        ref = nn.BatchNorm(use_running_average=True)
        yr = ref.apply(variables, x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(yr),
                                   atol=2e-5)

    def test_bf16_io_fp32_stats(self):
        xb = jnp.asarray(
            np.random.RandomState(1).randn(2, 4, 4, 16), jnp.bfloat16)
        mod = bn.TpuBatchNorm(use_running_average=False)
        variables = mod.init(jax.random.PRNGKey(0), xb)
        y, upd = mod.apply(variables, xb, mutable=["batch_stats"])
        assert y.dtype == jnp.bfloat16
        assert upd["batch_stats"]["mean"].dtype == jnp.float32
        # per-channel mean of the normalized output ~ 0
        assert abs(float(jnp.mean(y.astype(jnp.float32)))) < 0.05


class TestResNetIntegration:
    def test_resnet_tpu_norm_matches_flax_norm(self):
        from horovod_tpu.models import resnet
        x = jnp.asarray(np.random.RandomState(2).randn(2, 32, 32, 3),
                        jnp.float32)
        m_tpu = resnet.ResNet18(num_classes=10, dtype=jnp.float32,
                                norm_impl="tpu")
        m_ref = resnet.ResNet18(num_classes=10, dtype=jnp.float32,
                                norm_impl="flax")
        v_tpu = m_tpu.init(jax.random.PRNGKey(0), x, train=True)
        v_ref = m_ref.init(jax.random.PRNGKey(0), x, train=True)
        lt, _ = m_tpu.apply(v_tpu, x, train=True, mutable=["batch_stats"])
        lr, _ = m_ref.apply(v_ref, x, train=True, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(lt), np.asarray(lr),
                                   rtol=1e-3, atol=1e-3)
