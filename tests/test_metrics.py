"""Telemetry plane (utils/metrics.py): registry semantics, rank-0
aggregation (= sum of per-rank registries), Prometheus round-trip,
the HTTP exposition server, and the negotiation-cycle piggyback that
makes the control plane the metrics transport."""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.run.launch import run
from horovod_tpu.utils import metrics as hvd_metrics

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


@pytest.fixture
def reg():
    """Fresh enabled process registry; restores the env default after."""
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()


class TestInstruments:
    def test_counter_sums(self, reg):
        c = reg.counter("t_c", "help text")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5

    def test_gauge_sets_and_incs(self, reg):
        g = reg.gauge("t_g")
        g.set(7)
        g.inc(-2)
        assert g.value == 5.0

    def test_histogram_bucket_placement(self, reg):
        h = reg.histogram("t_h", buckets=(1.0, 2.0, 4.0)).labels()
        for v in (0.5, 1.5, 1.5, 3.0, 99.0):
            h.observe(v)
        # per-bucket (non-cumulative) counts incl. the +Inf bucket
        assert h.counts == [1, 2, 1, 1]
        assert h.count == 5
        assert h.sum == pytest.approx(105.5)

    def test_histogram_rejects_unsorted_bounds(self):
        with pytest.raises(ValueError, match="not sorted"):
            hvd_metrics.Histogram((2.0, 1.0))

    def test_labeled_children_are_distinct(self, reg):
        fam = reg.counter("t_ops", labels=("op",))
        fam.labels(op="allreduce").inc(3)
        fam.labels(op="allgather").inc(1)
        assert fam.labels(op="allreduce").value == 3
        assert fam.labels(op="allgather").value == 1

    def test_reregistration_is_idempotent(self, reg):
        assert reg.counter("t_same") is reg.counter("t_same")

    def test_kind_mismatch_raises(self, reg):
        reg.counter("t_kind")
        with pytest.raises(ValueError, match="re-registered"):
            reg.gauge("t_kind")

    def test_bucket_mismatch_raises(self, reg):
        reg.histogram("t_b", buckets=(1.0, 2.0))
        with pytest.raises(ValueError, match="different buckets"):
            reg.histogram("t_b", buckets=(1.0, 3.0))

    def test_event_ring_and_clock(self, reg):
        ev = reg.event("stall", tensor="grad0", missing_ranks=[1])
        assert ev["event"] == "stall" and ev["missing_ranks"] == [1]
        # shared timeline clock: ts_us on the monotonic base, epoch_us
        # the cross-rank-comparable anchor
        clock = hvd_metrics.shared_clock()
        assert ev["epoch_us"] == clock.epoch_us_at_ts0 + ev["ts_us"]
        assert reg.events()[-1] is ev


class TestAggregation:
    """The acceptance contract: rank-0 aggregation equals the sum of the
    per-rank registries."""

    def _rank_registry(self, rank):
        r = hvd_metrics.MetricsRegistry(rank=rank)
        r.counter("hvd_negotiation_cycles_total").inc(10 * (rank + 1))
        r.gauge("hvd_stalled_tensors").set(rank)
        h = r.histogram("hvd_negotiation_cycle_seconds",
                        buckets=(0.001, 0.01, 0.1))
        h.observe(0.005 * (rank + 1))
        r.counter("hvd_collective_bytes_total", labels=("op",)) \
            .labels(op="allreduce").inc(1024 * (rank + 1))
        r.event("marker", rank=rank)
        return r

    def test_merge_is_sum_of_per_rank_registries(self):
        regs = [self._rank_registry(r) for r in range(3)]
        agg = hvd_metrics.merge_snapshots([r.snapshot() for r in regs])
        assert agg["ranks"] == [0, 1, 2]
        m = agg["metrics"]
        assert m["hvd_negotiation_cycles_total"]["values"][0]["value"] \
            == 10 + 20 + 30
        assert m["hvd_stalled_tensors"]["values"][0]["value"] == 0 + 1 + 2
        hist = m["hvd_negotiation_cycle_seconds"]["values"][0]
        assert hist["count"] == 3
        assert hist["sum"] == pytest.approx(0.005 + 0.010 + 0.015)
        assert sum(hist["counts"]) == 3
        (ar,) = m["hvd_collective_bytes_total"]["values"]
        assert ar["labels"] == {"op": "allreduce"}
        assert ar["value"] == 1024 + 2048 + 3072
        # events concatenate ordered by the epoch anchor
        assert [e["rank"] for e in agg["events"]
                if e["event"] == "marker"] == [0, 1, 2]

    def test_bucket_bounds_mismatch_across_ranks_raises(self):
        a = hvd_metrics.MetricsRegistry(rank=0)
        b = hvd_metrics.MetricsRegistry(rank=1)
        a.histogram("h", buckets=(1.0, 2.0)).observe(1)
        b.histogram("h", buckets=(1.0, 3.0)).observe(1)
        with pytest.raises(ValueError, match="bucket bounds differ"):
            hvd_metrics.merge_snapshots([a.snapshot(), b.snapshot()])


class TestPrometheus:
    def _populated(self):
        r = hvd_metrics.MetricsRegistry(rank=0)
        r.counter("hvd_coordinator_cycles_total", "cycles").inc(42)
        r.gauge("hvd_stalled_ranks").set(2)
        r.counter("hvd_collective_bytes_total", labels=("op",)) \
            .labels(op="allreduce").inc(4096)
        h = r.histogram("hvd_flush_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.05, 5.0):
            h.observe(v)
        return r

    def test_round_trip_names_types_values(self):
        snap = self._populated().snapshot()
        text = hvd_metrics.render_prometheus(snap)
        parsed = hvd_metrics.parse_prometheus(text)
        assert parsed["hvd_coordinator_cycles_total"]["type"] == "counter"
        assert parsed["hvd_stalled_ranks"]["type"] == "gauge"
        assert parsed["hvd_flush_seconds"]["type"] == "histogram"
        (labels, v), = parsed["hvd_coordinator_cycles_total"]["samples"]
        assert v == 42
        samples = parsed["hvd_collective_bytes_total"]["samples"]
        assert samples == [({"op": "allreduce"}, 4096.0)]

    def test_histogram_buckets_cumulative_and_monotonic(self):
        snap = self._populated().snapshot()
        parsed = hvd_metrics.parse_prometheus(
            hvd_metrics.render_prometheus(snap))
        samples = parsed["hvd_flush_seconds"]["samples"]
        buckets = [(l["le"], v) for l, v in samples
                   if l.get("__series__") == "bucket"]
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), "bucket counts must be cumulative"
        assert buckets[-1][0] == "+Inf"
        total = [v for l, v in samples
                 if l.get("__series__") == "count"][0]
        assert counts[-1] == total == 4
        ssum = [v for l, v in samples if l.get("__series__") == "sum"][0]
        assert ssum == pytest.approx(5.105)

    def test_label_values_with_commas_and_quotes_survive(self):
        r = hvd_metrics.MetricsRegistry()
        r.counter("t_esc", labels=("k",)).labels(k='a,"b",c').inc()
        parsed = hvd_metrics.parse_prometheus(r.to_prometheus())
        (labels, v), = parsed["t_esc"]["samples"]
        assert labels["k"] == 'a,"b",c' and v == 1

    def test_histogram_quantile_interpolates(self):
        bounds = (1.0, 2.0, 4.0)
        counts = [0, 100, 0, 0]  # everything in (1, 2]
        q50 = hvd_metrics.histogram_quantile(bounds, counts, 0.5)
        assert 1.0 < q50 <= 2.0
        assert hvd_metrics.histogram_quantile(bounds, [0, 0, 0, 0],
                                              0.5) is None


class TestDisabled:
    def test_null_registry_is_inert(self):
        r = hvd_metrics.reset(enabled=False)
        try:
            assert not r.enabled
            r.counter("x").inc()
            r.gauge("y").labels(op="z").set(5)
            r.histogram("h").observe(1.0)
            assert r.event("stall") is None
            snap = r.snapshot()
            assert snap["metrics"] == {} and snap.get("disabled")
            assert r.to_prometheus() == ""
        finally:
            hvd_metrics.reset()

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("HVD_METRICS", "0")
        r = hvd_metrics.reset()
        try:
            assert isinstance(r, hvd_metrics.NullRegistry)
        finally:
            monkeypatch.delenv("HVD_METRICS")
            hvd_metrics.reset()


class TestHTTPServer:
    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
            return resp.read().decode()

    def test_scrape_round_trip_with_remote_aggregate(self):
        local = hvd_metrics.MetricsRegistry(rank=0)
        local.counter("hvd_negotiation_cycles_total").inc(5)
        remote = hvd_metrics.MetricsRegistry(rank=1)
        remote.counter("hvd_negotiation_cycles_total").inc(7)
        srv = hvd_metrics.MetricsServer(
            0, local.snapshot,
            remote_snapshots_fn=lambda: {1: remote.snapshot()})
        try:
            text = self._get(srv.port, "/metrics")
            parsed = hvd_metrics.parse_prometheus(text)
            (_, v), = parsed["hvd_negotiation_cycles_total"]["samples"]
            assert v == 12  # aggregate = local + remote
            data = json.loads(self._get(srv.port, "/metrics.json"))
            assert set(data["ranks"]) == {"0", "1"}
            agg = data["aggregate"]
            assert agg["ranks"] == [0, 1]
            assert agg["metrics"]["hvd_negotiation_cycles_total"][
                "values"][0]["value"] == 12
        finally:
            srv.close()

    def test_live_local_registry_wins_over_stale_self_snapshot(self):
        local = hvd_metrics.MetricsRegistry(rank=0)
        c = local.counter("hvd_coordinator_cycles_total")
        c.inc(3)
        stale = local.snapshot()
        c.inc(97)  # live value moves past the snapshot
        srv = hvd_metrics.MetricsServer(
            0, local.snapshot,
            remote_snapshots_fn=lambda: {0: stale})
        try:
            parsed = hvd_metrics.parse_prometheus(
                self._get(srv.port, "/metrics"))
            (_, v), = parsed["hvd_coordinator_cycles_total"]["samples"]
            assert v == 100  # not 103: the stale rank-0 snapshot dropped
        finally:
            srv.close()


class TestCoordinatorTelemetry:
    """Coordinator-side instruments and the snapshot piggyback, using
    the in-process CycleRequest harness (no processes involved)."""

    def _service(self, nproc=2, **cfg_kw):
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.ops import negotiation as neg
        cfg_kw.setdefault("stall_warning_time_seconds", 0)
        cfg = HorovodConfig(**cfg_kw)
        svc = neg.CoordinatorService(nproc, b"k" * 32,
                                     ports=[0], config=cfg)
        return svc, neg

    def _meta(self, neg, name, dtype="float32"):
        return neg.EntryMeta(name, "allreduce", dtype, (4,), 0, False)

    def test_cycle_counters_and_cache_hit_miss(self, reg):
        svc, neg = self._service()
        try:
            meta = self._meta(neg, "g")
            svc._handle(neg.CycleRequest(0, [meta], ack=-1, req_id=1),
                        ("127.0.0.1", 0))
            svc._handle(neg.CycleRequest(1, [meta], ack=-1, req_id=1),
                        ("127.0.0.1", 0))
            assert reg.counter("hvd_coordinator_cycles_total").value == 2
            assert reg.counter("hvd_response_cache_misses_total").value \
                == 2
            # steady state: the name EXECUTEd, so both ranks resubmit as
            # a cache hit
            cid = svc._cache_id_of["g"]
            hits = neg.encode_hits([cid])
            for r in (0, 1):
                svc._handle(neg.CycleRequest(r, [], ack=0, req_id=2,
                                             hits=hits),
                            ("127.0.0.1", 0))
            assert reg.counter("hvd_response_cache_hits_total").value == 2
            # an id the coordinator never issued scans as unknown
            resp = svc._handle(
                neg.CycleRequest(0, [], ack=0, req_id=3,
                                 hits=neg.encode_hits([cid + 999])),
                ("127.0.0.1", 0))
            assert resp.unknown_ids == (cid + 999,)
            assert reg.counter(
                "hvd_response_cache_unknown_ids_total").value == 1
            # tensors/cycle histogram saw every announcement
            h = reg.histogram("hvd_coordinator_tensors_per_cycle",
                              buckets=hvd_metrics.COUNT_BUCKETS).labels()
            assert h.count == 5
        finally:
            svc.shutdown()

    def test_wire_bytes_counter_tracks_encode_decode(self, reg):
        from horovod_tpu.ops import negotiation as neg
        resp = neg.CycleResponse(0, [], (64 << 20, 5.0), False)
        payload = neg.encode_response(resp)
        neg.decode_response(payload)
        fam = reg.counter("hvd_response_wire_bytes_total",
                          labels=("direction",))
        assert fam.labels(direction="out").value == len(payload)
        assert fam.labels(direction="in").value == len(payload)

    def test_piggybacked_snapshot_stored_and_aggregated(self, reg):
        svc, neg = self._service()
        try:
            reg.rank = 0
            worker = hvd_metrics.MetricsRegistry(rank=1)
            worker.counter("hvd_negotiation_cycles_total").inc(7)
            snap = worker.snapshot()
            svc._handle(neg.CycleRequest(1, [], ack=-1, req_id=1,
                                         metrics=snap),
                        ("127.0.0.1", 0))
            assert svc.metrics_snapshots[1] is snap
            # rank 0's exposition server serves the merged view
            srv = hvd_metrics.MetricsServer(
                0, reg.snapshot,
                remote_snapshots_fn=lambda: dict(svc.metrics_snapshots))
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}/metrics.json",
                        timeout=5) as r:
                    data = json.loads(r.read().decode())
            finally:
                srv.close()
            agg = data["aggregate"]
            assert agg["ranks"] == [0, 1]
            assert agg["metrics"]["hvd_negotiation_cycles_total"][
                "values"][0]["value"] == 7
            # rank 0's own coordinator counter rides the same aggregate
            assert agg["metrics"]["hvd_coordinator_cycles_total"][
                "values"][0]["value"] == 1
        finally:
            svc.shutdown()

    def test_stall_scan_sets_gauge_and_event_then_clears(self, reg):
        svc, neg = self._service(stall_warning_time_seconds=0.05)
        try:
            svc._submit(0, [self._meta(neg, "slow")])  # rank 1 missing
            time.sleep(0.08)
            svc._stall_scan()
            assert reg.gauge("hvd_stalled_ranks").value == 1
            assert reg.gauge("hvd_coordinator_stalled_tensors").value == 1
            (ev,) = [e for e in reg.events() if e["event"] == "stall"]
            assert ev["tensor"] == "slow"
            assert ev["missing_ranks"] == [1]
            assert ev["waited_s"] >= 0.05
            # one structured event per tensor, like the log line
            svc._stall_scan()
            assert len([e for e in reg.events()
                        if e["event"] == "stall"]) == 1
            # the laggard arrives: the row negotiates away and the
            # gauges CLEAR — stall state is current, not sticky
            svc._submit(1, [self._meta(neg, "slow")])
            svc._negotiate()
            svc._stall_scan()
            assert reg.gauge("hvd_stalled_ranks").value == 0
            assert reg.gauge("hvd_coordinator_stalled_tensors").value == 0
        finally:
            svc.shutdown()


class TestSatelliteInstrumentation:
    def test_fusion_plan_records_fill_fraction(self, reg):
        from horovod_tpu.ops import fusion
        leaves = [np.zeros((10,), np.float32) for _ in range(4)]  # 40 B
        fusion.plan_buckets(leaves, fusion_threshold=100)
        assert reg.counter("hvd_fusion_tensors_total").value == 4
        assert reg.counter("hvd_fusion_bytes_total").value == 160
        assert reg.counter("hvd_fusion_buckets_total").value == 2
        h = reg.histogram("hvd_fusion_fill_ratio",
                          buckets=hvd_metrics.RATIO_BUCKETS).labels()
        assert h.count == 2
        assert h.sum == pytest.approx(1.6)  # 80/100 + 80/100

    def test_fusion_plan_flags_oversized(self, reg):
        """A tensor at/over the threshold bypasses fusion — that must be
        loud (event + counter), not a mystery extra collective."""
        from horovod_tpu.ops import fusion
        leaves = [np.zeros((50,), np.float32),   # 200 B >= 100
                  np.zeros((10,), np.float32),
                  np.zeros((10,), np.float32)]
        buckets = fusion.plan_buckets(leaves, fusion_threshold=100)
        assert [b.indices for b in buckets] == [[0], [1, 2]]
        assert reg.counter("hvd_fusion_oversized_total").value == 1
        (ev,) = [e for e in reg.events()
                 if e["event"] == "oversized_tensor"]
        assert ev["index"] == 0
        assert ev["nbytes"] == 200
        assert ev["threshold"] == 100
        # threshold 0 = fusion disabled BY REQUEST: every tensor rides
        # alone, and none of that is "oversized"
        fusion.plan_buckets(leaves, fusion_threshold=0)
        assert reg.counter("hvd_fusion_oversized_total").value == 1
        # a bucket exactly filled by several members is not oversized
        fusion.plan_buckets([np.zeros((20,), np.float32),
                             np.zeros((5,), np.float32)],
                            fusion_threshold=100)
        assert reg.counter("hvd_fusion_oversized_total").value == 1

    def test_fusion_plan_never_mixes_dtypes(self, reg):
        from horovod_tpu.ops import fusion
        leaves = [np.zeros((4,), np.float32), np.zeros((4,), np.float16),
                  np.zeros((4,), np.float32), np.zeros((4,), np.float16)]
        buckets = fusion.plan_buckets(leaves, fusion_threshold=1 << 20)
        assert [b.indices for b in buckets] == [[0, 2], [1, 3]]
        for b in buckets:
            assert len({str(leaves[i].dtype) for i in b.indices}) == 1

    def test_chaos_injection_counts(self, reg):
        from horovod_tpu.run import chaos
        rules = chaos.parse_spec("negotiation:*:drop_request:1.0", seed=7)
        inj = chaos.ChaosInjector("negotiation", rules, delay_ms=0)
        assert inj.decide("request", "CycleRequest") == "drop_request"
        fam = reg.counter("hvd_chaos_injections_total",
                          labels=("fault",))
        assert fam.labels(fault="drop_request").value == 1
        (ev,) = [e for e in reg.events()
                 if e["event"] == "chaos_injection"]
        assert ev["fault"] == "drop_request"
        assert ev["service"] == "negotiation"

    def test_instrument_step_counts_and_throughput(self, reg):
        from horovod_tpu import trainer
        stepped = []

        def step(x):
            stepped.append(x)
            time.sleep(0.01)
            return x * 2

        wrapped = trainer.instrument_step(step, tokens_per_step=1024,
                                          name="unit")
        assert wrapped(3) == 6 and stepped == [3]
        m = reg.snapshot()["metrics"]
        (steps,) = m["hvd_steps_total"]["values"]
        assert steps["labels"] == {"loop": "unit"} and steps["value"] == 1
        (sec,) = m["hvd_step_seconds"]["values"]
        assert sec["count"] == 1 and sec["sum"] >= 0.01
        (tps,) = m["hvd_tokens_per_second"]["values"]
        assert 0 < tps["value"] <= 1024 / 0.01

    def test_instrument_step_mfu_gauge(self, reg):
        from horovod_tpu import trainer
        from horovod_tpu.utils import costmodel
        spec = costmodel.ChipSpec("test", 1e9, 1e9, 1e9)

        def step(x):
            time.sleep(0.01)
            return x

        wrapped = trainer.instrument_step(
            step, tokens_per_step=1000, name="unit",
            flops_per_token=1e6, spec=spec)
        wrapped(1)
        m = reg.snapshot()["metrics"]
        (mfu,) = m["hvd_mfu"]["values"]
        assert mfu["labels"] == {"loop": "unit"}
        # flops_per_step=1e9 at peak 1e9 → mfu = 1/dt seconds⁻¹·s;
        # dt ≥ 10ms → mfu ≤ 100, > 0
        assert 0 < mfu["value"] <= 100

    def test_instrument_step_no_mfu_without_spec_on_cpu(self, reg):
        from horovod_tpu import trainer
        wrapped = trainer.instrument_step(
            lambda x: x, tokens_per_step=10, name="unit",
            flops_per_token=100)  # spec auto-detect → cpu → no gauge
        wrapped(1)
        assert "hvd_mfu" not in reg.snapshot()["metrics"]

    def test_instrument_step_peak_hbm_gauge(self, reg, monkeypatch):
        # memory plane (docs/memory.md): allocator-backed peak bytes
        # next to the MFU gauge; CPU has no allocator stats, so the
        # probe is faked the way a TPU backend would answer
        from horovod_tpu import trainer
        from horovod_tpu.utils import memory as hvd_memory
        monkeypatch.setattr(hvd_memory, "step_peak_bytes",
                            lambda device=None: 12345)
        wrapped = trainer.instrument_step(lambda x: x, name="unit")
        wrapped(1)
        m = reg.snapshot()["metrics"]
        (peak,) = m["hvd_step_peak_hbm_bytes"]["values"]
        assert peak["labels"] == {"loop": "unit"}
        assert peak["value"] == 12345

    def test_instrument_step_no_peak_gauge_on_cpu(self, reg):
        # the CPU-null arm, mirroring the MFU gauge: no allocator
        # stats → the gauge is never created, not created-as-zero
        from horovod_tpu import trainer
        wrapped = trainer.instrument_step(lambda x: x, name="unit")
        wrapped(1)
        assert "hvd_step_peak_hbm_bytes" not in \
            reg.snapshot()["metrics"]

    def test_instrument_step_periodic_attribution(self, reg):
        import jax
        import jax.numpy as jnp

        from horovod_tpu import trainer
        f = jax.jit(lambda x: jnp.dot(x, x).sum())
        x = jnp.ones((64, 64))
        f(x).block_until_ready()  # compile outside the wrapper

        def step(x):
            out = f(x)
            out.block_until_ready()
            return out

        wrapped = trainer.instrument_step(step, name="unit",
                                          attrib_every=2)
        for _ in range(5):  # captures at steps 2 and 4
            wrapped(x)
        assert not [e for e in reg.events()
                    if e["event"] == "perf_attrib_error"]
        m = reg.snapshot()["metrics"]
        (busy,) = m["hvd_step_device_busy_frac"]["values"]
        assert busy["labels"] == {"loop": "unit"}
        assert busy["value"] >= 0
        classes = {v["labels"]["op_class"]
                   for v in m["hvd_step_breakdown_ms"]["values"]}
        assert "matmul" in classes
        # second capture has an EMA to drift against
        assert m["hvd_step_breakdown_drift"]["values"]
        assert m["hvd_step_exposed_comm_ms"]["values"]
        assert m["hvd_step_hidden_comm_ms"]["values"]

    def test_instrument_step_attrib_off_by_default(self, reg):
        from horovod_tpu import trainer
        wrapped = trainer.instrument_step(lambda x: x, name="unit")
        for _ in range(3):
            wrapped(1)
        m = reg.snapshot()["metrics"]
        assert "hvd_step_breakdown_ms" not in m
        assert "hvd_step_device_busy_frac" not in m

    def test_instrument_step_disabled_is_passthrough(self):
        hvd_metrics.reset(enabled=False)
        try:
            from horovod_tpu import trainer

            def step():
                return 1

            assert trainer.instrument_step(step) is step
        finally:
            hvd_metrics.reset()


class TestTwoRankEndpoints:
    """Acceptance: a 2-rank run with HVD_METRICS_PORT serves Prometheus
    and JSON endpoints, and rank 0's aggregate covers both ranks."""

    def test_two_rank_scrape_covers_both_ranks(self):
        def fn():
            import json as _json
            import os
            import time
            import urllib.request
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.utils import metrics as hm
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])

            # The negotiation control plane (and therefore the metrics
            # piggyback) is pure TCP and works everywhere; the XLA data
            # plane may not support multiprocess CPU — telemetry must
            # still flow, so execution failures are tolerated and the
            # data-plane assertions become conditional.
            data_plane_ok = True

            def reduce(name):
                nonlocal data_plane_ok
                h = hvd.allreduce_async(np.ones((64,), np.float32),
                                        average=False, name=name)
                try:
                    hvd.synchronize(h)
                except Exception:
                    data_plane_ok = False

            for i in range(3):
                reduce(f"m{i}")
            # outlive HVD_METRICS_INTERVAL so the next flush piggybacks
            # a fresh worker snapshot onto the negotiation cycle
            time.sleep(0.3)
            reduce("late")
            port = int(os.environ["HVD_METRICS_PORT"]) + r
            deadline = time.monotonic() + 10
            data = text = None
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=5) as resp:
                    text = resp.read().decode()
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics.json",
                        timeout=5) as resp:
                    data = _json.loads(resp.read().decode())
                if r != 0 or len(data["aggregate"].get("ranks", [])) == 2:
                    break
                time.sleep(0.2)
            parsed = hm.parse_prometheus(text)
            agg = data["aggregate"]["metrics"]
            cyc = parsed.get("hvd_negotiation_cycle_seconds",
                             {"samples": []})["samples"]
            bucket_counts = [v for l, v in cyc
                             if l.get("__series__") == "bucket"]
            out = {
                "rank": r,
                "data_plane_ok": data_plane_ok,
                "prom_names": sorted(parsed.keys()),
                "agg_ranks": data["aggregate"].get("ranks", []),
                "agg_cycles": agg.get(
                    "hvd_negotiation_cycles_total",
                    {"values": [{"value": 0}]})["values"][0]["value"],
                "coord_cycles": agg.get(
                    "hvd_coordinator_cycles_total",
                    {"values": [{"value": 0}]})["values"][0]["value"],
                "buckets_monotonic":
                    bucket_counts == sorted(bucket_counts),
            }
            hvd.shutdown()
            return out

        base = 19100 + (os.getpid() % 1000)
        env = dict(_ENV)
        env["HVD_METRICS_PORT"] = str(base)
        env["HVD_METRICS_INTERVAL"] = "0.1"
        results = run(fn, num_proc=2, env=env)
        by_rank = {res["rank"]: res for res in results}
        for res in results:
            assert "hvd_negotiation_cycles_total" in res["prom_names"]
            if res["data_plane_ok"]:
                assert "hvd_collective_bytes_total" in res["prom_names"]
            assert res["buckets_monotonic"]
        r0 = by_rank[0]
        assert r0["agg_ranks"] == [0, 1], r0
        assert "hvd_coordinator_cycles_total" in r0["prom_names"]
        assert r0["coord_cycles"] >= 4  # >= one cycle per rank per tensor
        # aggregate cycles = both ranks' worth: strictly more than any
        # single rank could have contributed alone
        assert r0["agg_cycles"] > by_rank[1]["agg_cycles"] / 2, results
