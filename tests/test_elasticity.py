"""Elasticity plane (docs/elasticity.md): the controller's hysteresis
and canary-style grading, the graceful-drain lifecycle's zero-loss and
bounded-timeout edges, admission shedding with drain-rate retry-after,
per-replica circuit breakers, and the staleness exclusion that keeps a
silent replica from absorbing all traffic. All process-local on the
same four-method engine double tests/test_router.py uses; the
multi-process flap-storm and overload drills ride
tests/test_chaos_plane.py."""

import pytest

from horovod_tpu.router import (CircuitBreaker, ElasticityController,
                                Router)
from horovod_tpu.router import elastic as route_elastic
from horovod_tpu.serving.engine import ServeEngine
from horovod_tpu.serving.queue import Request, RequestResult
from horovod_tpu.utils import metrics as hvd_metrics


@pytest.fixture
def reg():
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()


def _value(snap, name, **labels):
    fam = snap["metrics"].get(name)
    if fam is None:
        return None
    for v in fam["values"]:
        if all(v["labels"].get(k) == lv for k, lv in labels.items()):
            return v.get("value", v.get("count"))
    return None


def _events(snap, kind):
    return [e for e in snap["events"] if e["event"] == kind]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeEngine:
    """ServeEngine stand-in (same surface tests/test_router.py uses)."""

    def __init__(self, accept=True, generation=1):
        self.accept = accept
        self.generation = generation
        self.queue = []
        self.held = {}
        self.load = None
        self._done = []

    def submit(self, request):
        if not self.accept:
            return False
        self.held[request.request_id] = request
        return True

    @property
    def active_count(self):
        return len(self.held)

    def load_snapshot(self):
        if self.load is not None:
            return dict(self.load)
        return {"queue_depth": 0, "active_slots": len(self.held),
                "work_tokens": sum(r.max_new_tokens
                                   for r in self.held.values()),
                "free_slots": 8 - len(self.held), "free_blocks": 8,
                "generation": self.generation,
                "armed_generation": None}

    def finish(self, request_id, tokens=(5, 6, 7), ttft_s=0.01):
        req = self.held.pop(request_id)
        self._done.append(RequestResult(
            req.request_id, tuple(tokens), "completed", ttft_s=ttft_s,
            generation=self.generation))

    def step(self):
        out, self._done = self._done, []
        return out


class FakeRouter:
    """Just enough router surface for controller-only unit tests."""

    def __init__(self, live=(0,)):
        self.live = list(live)
        self.spawns_pending = 0
        self.drained = []

    def live_replicas(self):
        return sorted(self.live)

    def note_spawn_pending(self):
        self.spawns_pending += 1

    def begin_drain(self, rid):
        if rid not in self.live:
            return False
        self.live.remove(rid)
        self.drained.append(rid)
        return True


def _req(i, prompt=None, max_new_tokens=8):
    return Request(request_id=f"r{i}",
                   prompt=prompt if prompt is not None
                   else (100 + i, 200 + i, 300 + i),
                   max_new_tokens=max_new_tokens)


def _result(i, outcome="completed", ttft_s=0.01, tokens=(1, 2, 3)):
    return RequestResult(f"g{i}", tuple(tokens), outcome,
                         ttft_s=ttft_s)


def _ctrl(clock, spawn=None, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 0)
    kw.setdefault("dwell_s", 5.0)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("ttft_slo_s", 1.0)
    kw.setdefault("up_depth", 4.0)
    kw.setdefault("down_util", 0.25)
    kw.setdefault("window", 4)
    return ElasticityController(spawn=spawn, clock=clock, **kw)


PRESSURE = {"queue_depth": 10, "active_slots": 8, "free_slots": 0,
            "free_blocks": 4}
IDLE = {"queue_depth": 0, "active_slots": 0, "free_slots": 8,
        "free_blocks": 8}


# ---------------------------------------------------------------------------
# ElasticityController: hysteresis
# ---------------------------------------------------------------------------

class TestElasticHysteresis:
    def test_pressure_must_dwell_before_scale_up(self, reg):
        clock = FakeClock()
        spawned = []
        rt = FakeRouter([0])
        ctrl = _ctrl(clock, spawn=lambda r: spawned.append(1) or 7)
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        assert not spawned  # first sighting only starts the dwell
        clock.t = 4.9
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        assert not spawned
        clock.t = 5.0
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        assert spawned and rt.spawns_pending == 1
        snap = reg.snapshot()
        assert _value(snap, "hvd_elastic_changes_total",
                      action="scale_up") == 1
        (ev,) = _events(snap, "route_elastic_scale_up")
        assert ev["queue_depth"] == 10 and ev["replica"] == 7

    def test_pressure_blip_resets_the_dwell(self, reg):
        clock = FakeClock()
        spawned = []
        rt = FakeRouter([0])
        ctrl = _ctrl(clock, spawn=lambda r: spawned.append(1) or 7)
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        clock.t = 3.0
        ctrl.tick(rt, {0: dict(IDLE, queue_depth=1)}, clock.t)  # blip
        clock.t = 6.0
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        assert not spawned  # the dwell restarted at t=6
        clock.t = 11.0
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        assert spawned

    def test_cooldown_gates_the_next_change(self, reg):
        clock = FakeClock()
        rt = FakeRouter([0])
        ctrl = _ctrl(clock, spawn=lambda r: 7, window=1)
        clock.t = 5.0
        ctrl.tick(rt, {0: dict(PRESSURE)}, 0.0)
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)  # executes at t=5
        assert rt.spawns_pending == 1
        # grade it benignly so only the cooldown is in the way
        ctrl.observe(_result(1))
        ctrl._maybe_grade(rt, clock.t)
        assert ctrl.state == "steady"
        for t in (6.0, 10.0, 14.9):
            clock.t = t
            ctrl.tick(rt, {0: dict(PRESSURE)}, t)
        assert rt.spawns_pending == 1  # still inside the cooldown
        clock.t = 20.0
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        assert rt.spawns_pending == 2

    def test_max_replicas_caps_scale_up(self, reg):
        clock = FakeClock(10.0)
        rt = FakeRouter([0, 1])
        ctrl = _ctrl(clock, spawn=lambda r: 7, max_replicas=2)
        loads = {0: dict(PRESSURE), 1: dict(PRESSURE)}
        ctrl.tick(rt, loads, 0.0)
        ctrl.tick(rt, loads, 10.0)
        assert rt.spawns_pending == 0

    def test_idle_scale_down_drains_cheapest_and_respects_floor(
            self, reg):
        clock = FakeClock()
        rt = FakeRouter([0, 1])
        ctrl = _ctrl(clock, min_replicas=1)
        loads = {0: dict(IDLE, active_slots=1, free_slots=7),
                 1: dict(IDLE)}
        ctrl.tick(rt, loads, 0.0)
        ctrl.tick(rt, loads, 5.0)
        assert rt.drained == [1]  # the idler replica is the victim
        snap = reg.snapshot()
        assert _value(snap, "hvd_elastic_changes_total",
                      action="scale_down") == 1
        (ev,) = _events(snap, "route_elastic_scale_down")
        assert ev["replica"] == 1
        # at the floor, idle pressure never drains the last replica
        ctrl._grade = None
        ctrl.state = "steady"
        ctrl._last_change_ts = None
        ctrl.tick(rt, {0: dict(IDLE)}, 20.0)
        ctrl.tick(rt, {0: dict(IDLE)}, 30.0)
        assert rt.drained == [1]

    def test_kv_starvation_and_ttft_are_pressure(self, reg):
        clock = FakeClock()
        rt = FakeRouter([0])
        ctrl = _ctrl(clock, spawn=lambda r: 7)
        starved = dict(IDLE, queue_depth=1, free_blocks=0)
        ctrl.tick(rt, starved and {0: starved}, 0.0)
        ctrl.tick(rt, {0: starved}, 5.0)
        assert rt.spawns_pending == 1
        (ev,) = _events(reg.snapshot(), "route_elastic_scale_up")
        assert ev["kv_starved"] is True
        # breached TTFT alone is pressure even with shallow queues
        ctrl2 = _ctrl(clock, spawn=lambda r: 8, ttft_slo_s=0.5)
        for i in range(3):
            ctrl2.observe(_result(i, ttft_s=2.0))
        busy = dict(IDLE, queue_depth=1, active_slots=4, free_slots=4)
        ctrl2.tick(rt, {0: dict(busy)}, 10.0)
        ctrl2.tick(rt, {0: dict(busy)}, 15.0)
        assert rt.spawns_pending == 2

    def test_pressure_gauge_tracks_the_band(self, reg):
        clock = FakeClock()
        rt = FakeRouter([0])
        ctrl = _ctrl(clock)
        ctrl.tick(rt, {0: dict(PRESSURE)}, 0.0)
        assert _value(reg.snapshot(), "hvd_elastic_pressure") == 1
        ctrl.tick(rt, {0: dict(IDLE)}, 1.0)
        assert _value(reg.snapshot(), "hvd_elastic_pressure") == -1
        ctrl.tick(rt, {0: dict(IDLE, queue_depth=1, active_slots=4,
                               free_slots=4)}, 2.0)
        assert _value(reg.snapshot(), "hvd_elastic_pressure") == 0


# ---------------------------------------------------------------------------
# ElasticityController: canary-style grading
# ---------------------------------------------------------------------------

class TestElasticGrading:
    def _scale_down(self, clock, rt, ctrl):
        loads = {0: dict(IDLE), 1: dict(IDLE)}
        ctrl.tick(rt, loads, clock.t)
        clock.t += 5.0
        ctrl.tick(rt, loads, clock.t)
        assert rt.drained and ctrl.state == "grading"

    def test_benign_scale_down_promotes(self, reg):
        clock = FakeClock()
        rt = FakeRouter([0, 1])
        ctrl = _ctrl(clock, spawn=lambda r: 9, window=4)
        for i in range(4):
            ctrl.observe(_result(i))  # the pre-change baseline
        self._scale_down(clock, rt, ctrl)
        for i in range(4):
            ctrl.observe(_result(10 + i))  # unchanged SLO after
        clock.t += 1.0
        ctrl.tick(rt, {0: dict(IDLE, queue_depth=1, active_slots=4,
                               free_slots=4)}, clock.t)
        assert ctrl.state == "steady"
        assert rt.spawns_pending == 0  # no rollback
        (verdict, evidence) = ctrl.decisions[-1]
        assert verdict == "promote" and evidence["breaches"] == []
        assert _events(reg.snapshot(), "route_elastic_promote")

    def test_breached_scale_down_rolls_back_by_respawning(self, reg):
        clock = FakeClock()
        rt = FakeRouter([0, 1])
        respawned = []
        ctrl = _ctrl(clock, spawn=lambda r: respawned.append(9) or 9,
                     window=4, ttft_x=1.5, min_delta_s=0.025)
        for i in range(4):
            ctrl.observe(_result(i, ttft_s=0.01))
        self._scale_down(clock, rt, ctrl)
        for i in range(4):
            ctrl.observe(_result(10 + i, ttft_s=1.5))  # SLO got worse
        clock.t += 1.0
        ctrl.tick(rt, {0: dict(IDLE)}, clock.t)
        assert ctrl.state == "steady"
        assert respawned == [9] and rt.spawns_pending == 1
        (verdict, evidence) = ctrl.decisions[-1]
        assert verdict == "rollback"
        assert "ttft_p99" in evidence["breaches"]
        assert evidence["respawned"] == 9
        snap = reg.snapshot()
        assert _value(snap, "hvd_elastic_changes_total",
                      action="rollback") == 1
        (ev,) = _events(snap, "route_elastic_rollback")
        assert ev["action"] == "scale_down"
        assert [t["action"] for t in ctrl.transitions] == \
            ["scale_down", "rollback"]

    def test_one_change_at_a_time_while_grading(self, reg):
        clock = FakeClock()
        rt = FakeRouter([0, 1])
        ctrl = _ctrl(clock, spawn=lambda r: 9, window=4)
        for i in range(4):
            ctrl.observe(_result(i))
        self._scale_down(clock, rt, ctrl)
        clock.t += 20.0  # well past dwell AND cooldown
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        clock.t += 5.0
        ctrl.tick(rt, {0: dict(PRESSURE)}, clock.t)
        assert rt.spawns_pending == 0  # the grade still holds the lock

    def test_baseline_freezes_before_the_change(self, reg):
        clock = FakeClock()
        rt = FakeRouter([0, 1])
        ctrl = _ctrl(clock, window=4)
        for i in range(4):
            ctrl.observe(_result(i, ttft_s=0.01))
        self._scale_down(clock, rt, ctrl)
        base = ctrl._grade["baseline"]
        n_before = base.n
        ctrl.observe(_result(99, ttft_s=9.0))  # post-change result
        assert base.n == n_before  # never contaminates the 'before'
        assert ctrl._grade["after"].n == 1


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def _breaker(self, clock, **kw):
        kw.setdefault("fails", 3)
        kw.setdefault("probe_s", 2.0)
        kw.setdefault("close_n", 2)
        kw.setdefault("timeout_s", 10.0)
        return CircuitBreaker(clock=clock, **kw)

    def test_consecutive_failures_trip_open(self, reg):
        clock = FakeClock()
        br = self._breaker(clock)
        br.record_failure(0)
        br.record_failure(0)
        assert br.state(0) == route_elastic.CLOSED
        br.record_failure(0)
        assert br.state(0) == route_elastic.OPEN
        allowed, probe = br.filter([0, 1])
        assert allowed == [1] and probe is None  # probe not due yet
        snap = reg.snapshot()
        assert _value(snap, "hvd_route_breaker_state", replica="0") == 2
        assert _value(snap, "hvd_route_breaker_trips_total",
                      reason="dispatch_failed") == 1

    def test_success_resets_the_failure_streak(self, reg):
        clock = FakeClock()
        br = self._breaker(clock)
        br.record_failure(0)
        br.record_failure(0)
        br.record_success(0)
        br.record_failure(0)
        br.record_failure(0)
        assert br.state(0) == route_elastic.CLOSED

    def test_probe_halfopen_close_cycle(self, reg):
        clock = FakeClock()
        br = self._breaker(clock)
        for _ in range(3):
            br.record_failure(0)
        clock.t = 1.0
        allowed, probe = br.filter([0])
        assert probe is None  # first probe waits the full interval
        clock.t = 2.5
        allowed, probe = br.filter([0])
        assert allowed == [] and probe == 0
        br.mark_probe(0)
        _, again = br.filter([0])
        assert again is None  # one probe per interval, not a flood
        br.record_success(0)
        assert br.state(0) == route_elastic.HALF_OPEN
        br.record_success(0)
        assert br.state(0) == route_elastic.CLOSED
        snap = reg.snapshot()
        states = [e["state"] for e in _events(snap, "route_breaker")]
        assert states == ["open", "half_open", "closed"]
        assert _value(snap, "hvd_route_breaker_state", replica="0") == 0

    def test_halfopen_failure_retrips(self, reg):
        clock = FakeClock()
        br = self._breaker(clock)
        for _ in range(3):
            br.record_failure(0)
        clock.t = 2.5
        br.filter([0])
        br.mark_probe(0)
        br.record_success(0)
        assert br.state(0) == route_elastic.HALF_OPEN
        br.record_failure(0)
        assert br.state(0) == route_elastic.OPEN
        assert _value(reg.snapshot(), "hvd_route_breaker_trips_total",
                      reason="half_open_dispatch_failed") == 1

    def test_stale_and_wedged_trip_immediately(self, reg):
        clock = FakeClock()
        br = self._breaker(clock)
        br.note_stale(3)
        assert br.state(3) == route_elastic.OPEN
        br.note_wedged(4, age_s=12.5)
        assert br.state(4) == route_elastic.OPEN
        snap = reg.snapshot()
        assert _value(snap, "hvd_route_breaker_trips_total",
                      reason="stale_snapshot") == 1
        assert _value(snap, "hvd_route_breaker_trips_total",
                      reason="wedged") == 1
        wedge = [e for e in _events(snap, "route_breaker")
                 if e["reason"] == "wedged"]
        assert wedge[0]["age_s"] == 12.5


# ---------------------------------------------------------------------------
# Router: staleness exclusion (the silent-replica regression)
# ---------------------------------------------------------------------------

class TestStaleExclusion:
    def test_silent_replica_no_longer_absorbs_all_traffic(self, reg):
        # the bug this pins: policy.score(None/stale-idle) == 0.0 is
        # the MOST attractive score, so a replica that stopped
        # reporting looked freshly idle forever and won every dispatch
        clock = FakeClock(10.0)
        busy, silent = FakeEngine(), FakeEngine()
        busy.load = {"queue_depth": 6, "active_slots": 8,
                     "free_slots": 0, "free_blocks": 8}
        silent.load = {"queue_depth": 0, "active_slots": 0,
                       "free_slots": 8, "free_blocks": 8, "ts": 0.0}
        router = Router({0: busy, 1: silent}, policy="least_loaded",
                        stale_s=5.0, shed_depth=0, clock=clock)
        assert router.submit(_req(1))
        # replica 1 scores far better but its snapshot is 10s old
        assert router.inflight["r1"] == 0

    def test_stale_exclusion_feeds_the_breaker(self, reg):
        clock = FakeClock(10.0)
        busy, silent = FakeEngine(), FakeEngine()
        silent.load = {"queue_depth": 0, "ts": 0.0}
        br = CircuitBreaker(fails=3, probe_s=60.0, clock=clock)
        router = Router({0: busy, 1: silent}, breaker=br,
                        stale_s=5.0, shed_depth=0, clock=clock)
        router.submit(_req(1))
        assert br.state(1) == route_elastic.OPEN

    def test_all_stale_falls_back_to_dispatching(self, reg):
        # availability beats discipline: when EVERY snapshot is stale
        # the router keeps dispatching rather than failing everything
        clock = FakeClock(10.0)
        a, b = FakeEngine(), FakeEngine()
        a.load = {"queue_depth": 0, "ts": 0.0}
        b.load = {"queue_depth": 0, "ts": 0.0}
        router = Router({0: a, 1: b}, stale_s=5.0, shed_depth=0,
                        clock=clock)
        assert router.submit(_req(1))

    def test_never_reported_grace_window(self, reg):
        clock = FakeClock(0.0)
        router = Router({0: FakeEngine()}, stale_s=5.0, clock=clock)
        # within the post-add grace window an unreported replica stays
        # routable (a brand-new spawn has not heartbeated yet)...
        fresh, probe = router._usable([0, 7], {0: {"ts": 0.0}}, 0.0)
        assert fresh == [0, 7]
        router._first_seen[7] = 0.0
        # ...and past it, forever-silent means excluded
        fresh, _ = router._usable([0, 7], {0: {"ts": 10.0}}, 10.0)
        assert fresh == [0]

    def test_stale_zero_disables(self, reg):
        clock = FakeClock(10.0)
        eng = FakeEngine()
        eng.load = {"queue_depth": 0, "ts": 0.0}
        router = Router({0: eng}, stale_s=0.0, shed_depth=0,
                        clock=clock)
        assert router.submit(_req(1))
        assert router.inflight["r1"] == 0


# ---------------------------------------------------------------------------
# Router: overload shedding
# ---------------------------------------------------------------------------

class TestShedding:
    def _saturated(self, depth=8):
        eng = FakeEngine()
        eng.load = {"queue_depth": depth, "active_slots": 8,
                    "free_slots": 0, "free_blocks": 4}
        return eng

    def test_sheds_when_every_replica_is_deep(self, reg):
        router = Router({0: self._saturated(), 1: self._saturated()},
                        shed_depth=4, stale_s=0, clock=FakeClock())
        assert router.submit(_req(1)) is False
        assert router.last_shed["reason"] == "queue_depth"
        assert router.last_shed["retry_after_s"] == 1.0  # no rate yet
        snap = reg.snapshot()
        assert _value(snap, "hvd_route_shed_total",
                      reason="queue_depth") == 1
        (ev,) = _events(snap, "route_shed")
        assert ev["request_id"] == "r1" and ev["retry_after_s"] == 1.0
        assert not router.inflight  # rejected AT admission

    def test_kv_exhaustion_reason_when_all_out_of_blocks(self, reg):
        eng = FakeEngine()
        eng.load = {"queue_depth": 0, "free_blocks": 0}
        router = Router({0: eng}, shed_depth=4, stale_s=0,
                        clock=FakeClock())
        assert router.submit(_req(1)) is False
        assert router.last_shed["reason"] == "kv_exhausted"

    def test_headroom_anywhere_admits(self, reg):
        idle = FakeEngine()
        router = Router({0: self._saturated(), 1: idle}, shed_depth=4,
                        stale_s=0, clock=FakeClock())
        assert router.submit(_req(1))
        assert router.inflight["r1"] == 1

    def test_shed_depth_zero_disables(self, reg):
        router = Router({0: self._saturated()}, shed_depth=0,
                        stale_s=0, clock=FakeClock())
        assert router.submit(_req(1))

    def test_retry_after_prices_from_the_drain_rate(self, reg):
        clock = FakeClock()
        eng = FakeEngine()
        router = Router({0: eng}, shed_depth=4, stale_s=0, clock=clock)
        # two completions one second apart -> 1 req/s drain rate
        router.submit(_req(1))
        router.submit(_req(2))
        eng.finish("r1")
        clock.t = 1.0
        router.step()
        eng.finish("r2")
        clock.t = 2.0
        router.step()
        eng.load = {"queue_depth": 7, "active_slots": 8,
                    "free_slots": 0, "free_blocks": 4}
        assert router.submit(_req(3)) is False
        # 2 completions over the 1s since the first one -> 2 req/s;
        # depth 7 -> (7+1)/2 = 4s until the backlog clears
        assert router.last_shed["retry_after_s"] == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# Router: graceful drain
# ---------------------------------------------------------------------------

class TestGracefulDrain:
    def test_drain_excludes_dispatch_but_finishes_inflight(self, reg):
        clock = FakeClock()
        a, b = FakeEngine(), FakeEngine()
        router = Router({0: a, 1: b}, stale_s=0, shed_depth=0,
                        clock=clock)
        router.submit(_req(1, prompt=(1, 2, 3)))
        victim = router.inflight["r1"]
        assert router.begin_drain(victim)
        assert router.live_replicas() == [1 - victim]
        snap = reg.snapshot()
        (ev,) = _events(snap, "route_drain_begin")
        assert ev["replica"] == victim and ev["inflight"] == ["r1"]
        assert _value(snap, "hvd_route_replicas_draining") == 1
        # new work only lands on the survivor
        router.submit(_req(2, prompt=(9, 9, 9)))
        assert router.inflight["r2"] == 1 - victim
        # the draining engine keeps stepping: its request completes
        (a if victim == 0 else b).finish("r1")
        clock.t = 1.0
        results = router.step()
        assert [r.request_id for r in results] == ["r1"]
        assert results[0].outcome == "completed"
        assert not results[0].rerouted  # zero loss, no reroute
        handle = router._handles[victim]
        assert handle.state == handle.RETIRED
        snap = reg.snapshot()
        (done,) = _events(snap, "route_drain_done")
        assert done["replica"] == victim and done["drained_s"] == 1.0
        assert _value(snap, "hvd_route_replicas_draining") == 0

    def test_drain_timeout_reroutes_via_the_ledger(self, reg):
        clock = FakeClock()
        a, b = FakeEngine(), FakeEngine()
        router = Router({0: a, 1: b}, stale_s=0, shed_depth=0,
                        reroute_window_s=60.0, clock=clock)
        router.submit(_req(1, prompt=(1, 2, 3)))
        victim = router.inflight["r1"]
        wedged = a if victim == 0 else b
        survivor_eng = b if victim == 0 else a
        router.begin_drain(victim, timeout_s=5.0)
        clock.t = 6.0
        router.step()
        # force-retired: the remainder rerouted to the survivor
        assert router.inflight["r1"] == 1 - victim
        snap = reg.snapshot()
        (ev,) = _events(snap, "route_drain_timeout")
        assert ev["replica"] == victim and ev["rerouted"] == ["r1"]
        assert ev["drained_s"] == 6.0
        # a late completion from the retired engine can never
        # double-deliver: the engine is no longer stepped
        wedged.finish("r1")
        survivor_eng.finish("r1")
        results = router.step()
        assert [r.request_id for r in results] == ["r1"]
        assert results[0].replica == 1 - victim
        assert results[0].rerouted

    def test_reroute_window_expiry_racing_drain(self, reg):
        # the request is older than the reroute window by the time the
        # drain deadline fires: it must fail loudly, never resurrect
        clock = FakeClock()
        a, b = FakeEngine(), FakeEngine()
        router = Router({0: a, 1: b}, stale_s=0, shed_depth=0,
                        reroute_window_s=5.0, clock=clock)
        router.submit(_req(1, prompt=(1, 2, 3)))
        victim = router.inflight["r1"]
        router.begin_drain(victim, timeout_s=10.0)
        clock.t = 11.0  # past BOTH the drain bound and the window
        router.step()
        results = router.step()  # loss-path failures drain next step
        assert [r.request_id for r in results] == ["r1"]
        assert results[0].outcome == "failed"
        assert results[0].reason == "reroute_window"
        assert "r1" not in router.inflight

    def test_begin_drain_rejects_non_live(self, reg):
        router = Router({0: FakeEngine()}, clock=FakeClock())
        assert router.begin_drain(0)
        assert not router.begin_drain(0)  # already draining
        assert not router.begin_drain(42)  # unknown

    def test_drain_signals_the_engine(self, reg):
        eng = ServeEngine.__new__(ServeEngine)  # surface check only
        assert hasattr(eng, "begin_drain")
        a = FakeEngine()
        a.begin_drain = lambda: setattr(a, "drained", True)
        router = Router({0: a, 1: FakeEngine()}, clock=FakeClock())
        router.begin_drain(0)
        assert getattr(a, "drained", False)


# ---------------------------------------------------------------------------
# Router: scale-up + parked reroutes (no_survivors racing a spawn)
# ---------------------------------------------------------------------------

class TestScaleUpAndParked:
    def test_reroute_parks_against_pending_spawn(self, reg):
        clock = FakeClock()
        a = FakeEngine()
        router = Router({0: a}, stale_s=0, shed_depth=0,
                        reroute_window_s=30.0, clock=clock)
        router.submit(_req(1, prompt=(1, 2, 3)))
        router.note_spawn_pending()
        router.on_ranks_lost([0])
        # no survivors, but a spawn is mid-flight: parked, not failed
        assert not router.step()
        snap = reg.snapshot()
        (ev,) = _events(snap, "route_reroute_parked")
        assert ev["request_id"] == "r1" and ev["from_replica"] == 0
        # the landing spawn absorbs the parked reroute
        fresh = FakeEngine()
        clock.t = 1.0
        router.add_replica(1, fresh)
        assert router.inflight["r1"] == 1
        fresh.finish("r1")
        (res,) = router.step()
        assert res.outcome == "completed" and res.rerouted
        assert res.replica == 1
        assert _events(reg.snapshot(), "route_replica_added")

    def test_parked_reroute_expires_inside_the_window(self, reg):
        clock = FakeClock()
        router = Router({0: FakeEngine()}, stale_s=0, shed_depth=0,
                        reroute_window_s=5.0, clock=clock)
        router.submit(_req(1, prompt=(1, 2, 3)))
        router.note_spawn_pending()
        router.on_ranks_lost([0])
        clock.t = 6.0  # the spawn never lands; the window closes
        router.step()
        (res,) = router.step()
        assert res.outcome == "failed"
        assert res.reason == "reroute_window"
        assert not router._parked

    def test_without_pending_spawn_no_survivors_fails_loudly(self, reg):
        router = Router({0: FakeEngine()}, stale_s=0, shed_depth=0,
                        clock=FakeClock())
        router.submit(_req(1, prompt=(1, 2, 3)))
        router.on_ranks_lost([0])
        (res,) = router.step()
        assert res.outcome == "failed" and res.reason == "no_survivors"

    def test_add_replica_rejects_live_duplicate(self, reg):
        router = Router({0: FakeEngine()}, clock=FakeClock())
        with pytest.raises(ValueError):
            router.add_replica(0, FakeEngine())


# ---------------------------------------------------------------------------
# Router: breaker integration (probe dispatch, wedge detection)
# ---------------------------------------------------------------------------

class TestRouterBreaker:
    def test_rejected_dispatches_trip_and_probe_traffic_recovers(
            self, reg):
        clock = FakeClock()
        sick, ok = FakeEngine(accept=False), FakeEngine()
        sick.load = {"queue_depth": 0, "active_slots": 0,
                     "free_slots": 8, "free_blocks": 8}
        ok.load = {"queue_depth": 5, "active_slots": 8,
                   "free_slots": 0, "free_blocks": 8}
        br = CircuitBreaker(fails=2, probe_s=2.0, close_n=1,
                            clock=clock)
        router = Router({0: sick, 1: ok}, breaker=br, stale_s=0,
                        shed_depth=0, clock=clock)
        # the sick replica scores best, rejects twice, trips open
        assert router.submit(_req(1)) is False
        assert router.submit(_req(2)) is False
        assert br.state(0) == route_elastic.OPEN
        # while open, traffic flows to the scored-worse survivor
        assert router.submit(_req(3))
        assert router.inflight["r3"] == 1
        # probe window fires: the next request IS the probe
        sick.accept = True
        clock.t = 3.0
        assert router.submit(_req(4))
        assert router.inflight["r4"] == 0
        sick.finish("r4")
        router.step()
        assert br.state(0) == route_elastic.CLOSED  # close_n=1

    def test_wedged_inflight_trips_the_breaker(self, reg):
        clock = FakeClock()
        eng = FakeEngine()
        br = CircuitBreaker(fails=3, timeout_s=5.0, probe_s=60.0,
                            clock=clock)
        router = Router({0: eng, 1: FakeEngine()}, breaker=br,
                        stale_s=0, shed_depth=0, clock=clock)
        router.submit(_req(1))
        wedged_on = router.inflight["r1"]
        clock.t = 6.0  # held past the breaker timeout, never finished
        router.step()
        assert br.state(wedged_on) == route_elastic.OPEN
        trips = [e for e in _events(reg.snapshot(), "route_breaker")
                 if e["reason"] == "wedged"]
        assert trips and trips[0]["replica"] == wedged_on


# ---------------------------------------------------------------------------
# end-to-end: the controller drives a real Router
# ---------------------------------------------------------------------------

class TestElasticEndToEnd:
    def test_pressure_spawns_through_the_router(self, reg):
        clock = FakeClock()
        eng = FakeEngine()
        eng.load = {"queue_depth": 10, "active_slots": 8,
                    "free_slots": 0, "free_blocks": 8}

        def spawn(router):
            rid = max(router._handles) + 1
            return router.add_replica(rid, FakeEngine()).replica_id

        ctrl = ElasticityController(
            spawn=spawn, dwell_s=1.0, cooldown_s=100.0, window=4,
            up_depth=4.0, clock=clock)
        router = Router({0: eng}, elastic=ctrl, stale_s=0,
                        shed_depth=0, clock=clock)
        router.step()
        clock.t = 2.0
        router.step()
        assert router.live_replicas() == [0, 1]
        assert ctrl.state == "grading"
        (ev,) = _events(reg.snapshot(), "route_elastic_scale_up")
        assert ev["replica"] == 1

    def test_idle_drains_through_the_router(self, reg):
        clock = FakeClock()
        a, b = FakeEngine(), FakeEngine()
        ctrl = ElasticityController(
            spawn=None, dwell_s=1.0, cooldown_s=100.0, window=4,
            min_replicas=1, down_util=0.25, clock=clock)
        router = Router({0: a, 1: b}, elastic=ctrl, stale_s=0,
                        shed_depth=0, clock=clock)
        router.step()
        clock.t = 2.0
        router.step()
        assert len(router.live_replicas()) == 1
        assert router._draining or any(
            h.state == h.RETIRED for h in router._handles.values())
