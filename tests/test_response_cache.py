"""Response cache for the negotiated control plane (reference
response_cache.h:43-92 / response_cache.cc:317-354 + the RunBypass fast
path, operations.cc:1168-1215): steady-state resubmissions ride the wire
as cache-id bits instead of full EntryMetas, with invalidation on
signature change and recovery via unknown-id re-announcement."""

import numpy as np
import pytest

from horovod_tpu.run.launch import run

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


class TestHitCodec:
    def test_roundtrip(self):
        from horovod_tpu.ops import negotiation as neg
        for ids in ([], [0], [7], [0, 1, 2, 3], [5, 1000, 30000],
                    list(range(1000)), [999999], list(range(0, 4096, 3))):
            assert neg.decode_hits(neg.encode_hits(ids)) == sorted(ids)

    def test_dense_encoding_is_compact(self):
        from horovod_tpu.ops import negotiation as neg
        # 1000 steady-state tensors: ~1 bit each on the wire
        assert len(neg.encode_hits(list(range(1000)))) <= 130

    def test_sparse_encoding_is_bounded(self):
        from horovod_tpu.ops import negotiation as neg
        # one surviving stable name with a huge id must not cost
        # id/8 bytes (the varint arm wins over the bitset)
        assert len(neg.encode_hits([10_000_000])) < 8


class TestCoordinatorCache:
    def _service(self, nproc=2, capacity=1024, threshold=0):
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.ops import negotiation as neg
        cfg = HorovodConfig(fusion_threshold=threshold,
                            stall_warning_time_seconds=0,
                            cache_capacity=capacity)
        svc = neg.CoordinatorService(nproc, b"k" * 32, ports=[0],
                                     config=cfg)
        return svc, neg

    def _meta(self, neg, name, shape=(4,), dtype="float32",
              op="allreduce"):
        return neg.EntryMeta(name, op, dtype, shape, 0, False)

    def test_execute_assigns_cache_ids(self):
        svc, neg = self._service()
        try:
            m = self._meta(neg, "a")
            svc._submit(0, [m])
            svc._submit(1, [m])
            svc._negotiate()
            (r,) = svc._responses
            assert r.kind == r.EXECUTE and r.cache_ids == [0]
            assert svc._cache_id_of == {"a": 0}
        finally:
            svc.shutdown()

    def test_hit_resolves_to_cached_meta(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._service()
        try:
            m = self._meta(neg, "a")
            # round 1: full metas both ranks
            for rank in (0, 1):
                svc._handle(CycleRequest(rank, [m], -1, req_id=1), ("", 0))
            assert len(svc._responses) == 1
            # round 2: both ranks announce via hit bits only (ack=-1 so
            # the log is not pruned under the assertions)
            hits = neg.encode_hits([0])
            for rank in (0, 1):
                resp = svc._handle(
                    CycleRequest(rank, [], -1, req_id=2, hits=hits),
                    ("", 0))
                assert resp.unknown_ids == ()
            assert len(svc._responses) == 2
            assert svc._responses[1].names == ["a"]
            assert svc._responses[1].cache_ids == [0]  # id is stable
        finally:
            svc.shutdown()

    def test_unknown_id_reported(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._service()
        try:
            resp = svc._handle(
                CycleRequest(0, [], -1, req_id=1,
                             hits=neg.encode_hits([5])), ("", 0))
            assert resp.unknown_ids == (5,)
            assert svc._responses == []  # nothing planted
        finally:
            svc.shutdown()

    def test_changed_signature_invalidates_id(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._service()
        try:
            m = self._meta(neg, "a", shape=(4,))
            for rank in (0, 1):
                svc._handle(CycleRequest(rank, [m], -1, req_id=1), ("", 0))
            assert svc._cache_id_of == {"a": 0}
            # shape changes on both ranks (ragged last batch)
            m2 = self._meta(neg, "a", shape=(2,))
            for rank in (0, 1):
                svc._handle(CycleRequest(rank, [m2], -1, req_id=2),
                            ("", 0))
            # old id is gone; the new EXECUTE assigned a fresh one
            assert 0 not in svc._cache
            assert svc._cache_id_of == {"a": 1}
            assert svc._responses[1].cache_ids == [1]
            # a straggler hit on the dead id is unknown, not aliased
            resp = svc._handle(
                CycleRequest(0, [], -1, req_id=3,
                             hits=neg.encode_hits([0])), ("", 0))
            assert resp.unknown_ids == (0,)
        finally:
            svc.shutdown()

    def test_capacity_evicts_lru_and_never_reuses_ids(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._service(capacity=2)
        try:
            for i, name in enumerate(["a", "b", "c"]):
                m = self._meta(neg, name)
                for rank in (0, 1):
                    svc._handle(
                        CycleRequest(rank, [m], i - 1, req_id=i + 1),
                        ("", 0))
            assert sorted(svc._cache) == [1, 2]       # "a" (id 0) evicted
            assert sorted(svc._cache_id_of) == ["b", "c"]
            assert svc._next_cache_id == 3
            resp = svc._handle(
                CycleRequest(0, [], 2, req_id=9,
                             hits=neg.encode_hits([0])), ("", 0))
            assert resp.unknown_ids == (0,)
        finally:
            svc.shutdown()

    def test_capacity_zero_disables_caching(self):
        svc, neg = self._service(capacity=0)
        try:
            m = self._meta(neg, "a")
            svc._submit(0, [m])
            svc._submit(1, [m])
            svc._negotiate()
            (r,) = svc._responses
            assert r.cache_ids is None
            assert svc._cache == {}
        finally:
            svc.shutdown()

    def test_deduped_retry_returns_persisted_unknown_ids(self):
        """Lost-response regression (ADVICE.md, medium): the unknown-id
        verdict is resolved on the FIRST processing of a req_id and must
        be returned VERBATIM on a deduped retry. Before the fix the
        retry hit the dedupe arm and answered unknown_ids=() — the
        worker never learned its hits were stale, and the hit tensors
        waited in _negotiated_pending forever."""
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._service()
        try:
            hits = neg.encode_hits([5])  # id never assigned: unknown
            r1 = svc._handle(CycleRequest(0, [], -1, req_id=1, hits=hits),
                             ("", 0))
            assert r1.unknown_ids == (5,)
            # the response above is "lost on the wire"; the transport
            # retry resends the identical request (same req_id)
            r2 = svc._handle(CycleRequest(0, [], -1, req_id=1, hits=hits),
                             ("", 0))
            assert r2.unknown_ids == (5,), \
                "deduped retry dropped the unknown-id re-announce signal"
            # and a NEW req_id re-resolves fresh rather than replaying
            r3 = svc._handle(CycleRequest(0, [], -1, req_id=2), ("", 0))
            assert r3.unknown_ids == ()
        finally:
            svc.shutdown()

    def test_retry_with_hits_is_idempotent(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._service()
        try:
            m = self._meta(neg, "a")
            for rank in (0, 1):
                svc._handle(CycleRequest(rank, [m], -1, req_id=1), ("", 0))
            hits = neg.encode_hits([0])
            # rank 0's response was lost: the retry reuses req_id and
            # must not plant a second row
            for _ in range(2):
                svc._handle(CycleRequest(0, [], -1, req_id=2, hits=hits),
                            ("", 0))
            assert len(svc._table) == 1  # one pending row for "a", rank 0
            svc._handle(CycleRequest(1, [], -1, req_id=2, hits=hits),
                        ("", 0))
            # total ordered work = exactly two responses for "a"
            assert svc._base_seq + len(svc._responses) == 2
        finally:
            svc.shutdown()


class TestNegotiatedCacheEndToEnd:
    def test_steady_state_uses_hits_and_stays_correct(self):
        """Same gradient names over repeated steps: after step 1 every
        announcement is a cache bit, and results stay exact."""
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common import state
            hvd.init()
            outs = []
            for step in range(4):
                hs = [hvd.allreduce_async(
                    np.full((8,), float(step * 10 + i), np.float32),
                    average=False, name=f"grad{i}") for i in range(5)]
                outs.append([float(np.asarray(hvd.synchronize(h))[0])
                             for h in hs])
            coord = state.global_state().coordinator
            hits = coord._neg_hit_count
            cached = len(coord._neg_cache)
            hvd.shutdown()
            return outs, hits, cached

        results = run(fn, num_proc=2, env=_ENV)
        for outs, hits, cached in results:
            for step in range(4):
                assert outs[step] == \
                    [2.0 * (step * 10 + i) for i in range(5)]
            # steps 2-4 announce all 5 names via bits (step 1 may
            # partially hit if fused responses landed mid-step)
            assert hits >= 15, (hits, cached)
            assert cached == 5

    def test_shape_change_mid_run_invalidates_and_recovers(self):
        """The ragged-last-batch pattern: a cached name resubmitted with
        a new shape must invalidate cleanly and still reduce exactly."""
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            outs = []
            for shape in [(4,), (4,), (2,), (4,)]:
                h = hvd.allreduce_async(
                    np.full(shape, 3.0, np.float32), average=False,
                    name="g")
                out = np.asarray(hvd.synchronize(h))
                outs.append((out.shape, float(out[0])))
            hvd.shutdown()
            return outs

        results = run(fn, num_proc=2, env=_ENV)
        for outs in results:
            assert outs == [((4,), 6.0), ((4,), 6.0), ((2,), 6.0),
                            ((4,), 6.0)]
