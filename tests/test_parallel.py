"""Mesh construction and hierarchical (two-level) allreduce tests
(reference NCCLHierarchicalAllreduce semantics, nccl_operations.cc:162-379)."""

import numpy as np
import pytest


def test_build_mesh_axes(hvd):
    from horovod_tpu.parallel import mesh as mesh_mod
    m = mesh_mod.build_mesh(tp=2, sp=2)
    assert m.axis_names == ("dp", "pp", "tp", "sp", "ep")
    assert m.shape["dp"] == 2 and m.shape["tp"] == 2 and m.shape["sp"] == 2
    assert m.shape["pp"] == 1 and m.shape["ep"] == 1


def test_build_mesh_bad_factorization(hvd):
    from horovod_tpu.parallel import mesh as mesh_mod
    with pytest.raises(ValueError):
        mesh_mod.build_mesh(tp=3)


def test_hierarchical_allreduce_matches_flat(hvd):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import hierarchical, mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)
    x = np.arange(8.0 * 5).reshape(8, 5).astype(np.float32)

    def f(s):
        return hierarchical_fn(s[0])

    def hierarchical_fn(t):
        return hierarchical.hierarchical_allreduce(t, fast_axis="chips",
                                                   slow_axis="slices")

    out = jax.jit(jax.shard_map(
        f, mesh=m, in_specs=P(("slices", "chips")),
        out_specs=P(("slices", "chips"))))(x)
    # every worker's (5,) result is the global sum of rows; out_specs
    # concatenates the 8 per-worker results into (40,)
    expect = x.sum(axis=0)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 5)[0], expect,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 5)[7], expect,
                               rtol=1e-6)


def test_hierarchical_allreduce_padding(hvd):
    # tensor size not divisible by chips-per-slice (4) exercises the padding
    # path (nccl_operations.cc:210-216 analogue)
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import hierarchical, mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)
    x = np.arange(8.0 * 7).reshape(8, 7).astype(np.float32)

    def f(s):
        return hierarchical.hierarchical_allreduce(
            s[0], average=True)

    out = jax.jit(jax.shard_map(
        f, mesh=m, in_specs=P(("slices", "chips")),
        out_specs=P(("slices", "chips"))))(x)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 7)[3],
                               x.mean(axis=0), rtol=1e-6)


def test_hierarchical_allreduce_hlo_reduces_slow_axis_bytes(hvd):
    """The perf contract of the two-level path (the reference's most
    perf-critical op, nccl_operations.cc:162-379): from the COMPILED HLO,
    the inter-slice (slow/DCN) collective must operate on 1/chips_per_slice
    of the payload, between cross-slice replica groups — while the flat
    allreduce moves the full payload through one global group."""
    import re

    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel import hierarchical, mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)  # 2 slices x 4 chips
    n = 1024
    chips = m.shape["chips"]
    x = np.zeros((8, n), np.float32)

    def collectives(fn):
        """[(op, elements, replica_groups)] from the optimized HLO."""
        j = jax.jit(jax.shard_map(
            fn, mesh=m, in_specs=P(("slices", "chips")),
            out_specs=P(("slices", "chips"))))
        hlo = j.lower(x).compile().as_text()
        out = []
        pat = re.compile(
            r"f32\[(\d+)\]\S*\s+(all-reduce|reduce-scatter|all-gather)\("
            r".*?replica_groups=\{(\{[\d,{}]+\})\}")
        for line in hlo.splitlines():
            match = pat.search(line)
            if match:
                groups = [
                    tuple(int(i) for i in g.split(","))
                    for g in re.findall(r"\{([\d,]+)\}", match.group(3))]
                out.append((match.group(2), int(match.group(1)), groups))
        return out

    def hier(s):
        return hierarchical.hierarchical_allreduce(
            s[0], fast_axis="chips", slow_axis="slices")[None]

    def flat(s):
        return hierarchical.flat_allreduce(s[0], ("slices", "chips"))[None]

    intra = [(0, 1, 2, 3), (4, 5, 6, 7)]      # fast axis: within a slice
    cross = [(0, 4), (1, 5), (2, 6), (3, 7)]  # slow axis: across slices

    ops = collectives(hier)
    by_op = {op: (elems, groups) for op, elems, groups in ops}
    assert set(by_op) == {"reduce-scatter", "all-reduce", "all-gather"}, ops
    # phase 1: reduce-scatter over ICI leaves each chip 1/chips of the data
    assert by_op["reduce-scatter"] == (n // chips, intra), ops
    # phase 2 — THE point: the slow-axis collective carries only n/chips
    assert by_op["all-reduce"] == (n // chips, cross), ops
    # phase 3: all-gather over ICI rebuilds the full tensor
    assert by_op["all-gather"][0] == n and by_op["all-gather"][1] == intra

    flat_ops = collectives(flat)
    assert flat_ops == [
        ("all-reduce", n, [(0, 1, 2, 3, 4, 5, 6, 7)])], flat_ops
