"""Mesh construction and hierarchical (two-level) allreduce tests
(reference NCCLHierarchicalAllreduce semantics, nccl_operations.cc:162-379)."""

import numpy as np
import pytest


def test_build_mesh_axes(hvd):
    from horovod_tpu.parallel import mesh as mesh_mod
    m = mesh_mod.build_mesh(tp=2, sp=2)
    assert m.axis_names == ("dp", "pp", "tp", "sp", "ep")
    assert m.shape["dp"] == 2 and m.shape["tp"] == 2 and m.shape["sp"] == 2
    assert m.shape["pp"] == 1 and m.shape["ep"] == 1


def test_build_mesh_bad_factorization(hvd):
    from horovod_tpu.parallel import mesh as mesh_mod
    with pytest.raises(ValueError):
        mesh_mod.build_mesh(tp=3)


def test_hierarchical_allreduce_matches_flat(hvd):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import hierarchical, mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)
    x = np.arange(8.0 * 5).reshape(8, 5).astype(np.float32)

    def f(s):
        return hierarchical_fn(s[0])

    def hierarchical_fn(t):
        return hierarchical.hierarchical_allreduce(t, fast_axis="chips",
                                                   slow_axis="slices")

    out = jax.jit(jax.shard_map(
        f, mesh=m, in_specs=P(("slices", "chips")),
        out_specs=P(("slices", "chips"))))(x)
    # every worker's (5,) result is the global sum of rows; out_specs
    # concatenates the 8 per-worker results into (40,)
    expect = x.sum(axis=0)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 5)[0], expect,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 5)[7], expect,
                               rtol=1e-6)


def test_hierarchical_allreduce_padding(hvd):
    # tensor size not divisible by chips-per-slice (4) exercises the padding
    # path (nccl_operations.cc:210-216 analogue)
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import hierarchical, mesh as mesh_mod

    m = mesh_mod.build_hierarchical_mesh(num_slices=2)
    x = np.arange(8.0 * 7).reshape(8, 7).astype(np.float32)

    def f(s):
        return hierarchical.hierarchical_allreduce(
            s[0], average=True)

    out = jax.jit(jax.shard_map(
        f, mesh=m, in_specs=P(("slices", "chips")),
        out_specs=P(("slices", "chips"))))(x)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 7)[3],
                               x.mean(axis=0), rtol=1e-6)
