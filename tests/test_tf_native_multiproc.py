"""Multi-process TF native-ops tests, split from test_tf_native_ops.py so CI/review windows can chunk the process-spawning half separately."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from horovod_tpu.run.launch import run  # noqa: E402

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _native():
    from horovod_tpu.tensorflow import native
    if not native.available():
        pytest.skip("libhvd_tf.so unavailable (no TF headers / toolchain)")
    return native


class TestMultiProcess:
    def test_collectives_three_processes(self):
        def _collectives_worker():
            import os
            import numpy as np
            import tensorflow as tf
            from horovod_tpu.tensorflow import native

            rank = int(os.environ["HVD_PROCESS_ID"])
            size = int(os.environ["HVD_NUM_PROC"])
            if not native.available():
                return "unavailable"
            assert native.ensure_plane(rank, size)
            try:
                report = {}

                total = sum(r + 1 for r in range(size))
                ra = native.allreduce(
                    tf.constant(np.full(1000, rank + 1, np.float32)),
                    average=False, name="t.a")
                report["sum_f32"] = float(ra.numpy()[0])
                rb = native.allreduce(
                    tf.constant(np.arange(5, dtype=np.float64) * (rank + 1)),
                    average=True, name="t.b")
                report["avg_f64"] = rb.numpy().tolist()

                # 16-bit software sum (role of reference common/half.cc float16_sum)
                rc = native.allreduce(
                    tf.cast(tf.fill([64], float(rank + 1)), tf.bfloat16),
                    average=False, name="t.c")
                report["sum_bf16"] = float(tf.cast(rc, tf.float32).numpy()[0])
                rh = native.allreduce(
                    tf.cast(tf.fill([64], float(rank + 1)), tf.float16),
                    average=False, name="t.h")
                report["sum_f16"] = float(tf.cast(rh, tf.float32).numpy()[0])
                # subnormal f16 (2^-15 < 2^-14): the software sum must
                # decode subnormals at full value, not half
                rs = native.allreduce(
                    tf.fill([16], tf.cast(2.0 ** -15, tf.float16)),
                    average=False, name="t.s")
                report["sum_f16_subnormal"] = float(
                    tf.cast(rs, tf.float32).numpy()[0])

                # allgatherv: per-rank first dims differ (rank+1 rows)
                rg = native.allgather(
                    tf.constant(np.full((rank + 1, 3), rank, np.int32)), name="t.g")
                report["gathered"] = rg.numpy().tolist()

                rd = native.broadcast(
                    tf.constant(np.full(17, rank * 10.0, np.float32)),
                    root_rank=1, name="t.d")
                report["bcast"] = float(rd.numpy()[0])

                # compiled graph with TWO independent collectives: the executor
                # may schedule them in either order per rank; negotiation must
                # still run them in one agreed order everywhere
                @tf.function
                def step(t, u):
                    x = native.allreduce(t, average=True, name="s.g0")
                    y = native.allreduce(u, average=False, name="s.g1")
                    return x + y[: t.shape[0]]

                outs = []
                for i in range(4):
                    o = step(tf.fill([8], float(rank + i)), tf.fill([16], 1.0))
                    outs.append(float(o.numpy()[0]))
                report["steps"] = outs
                return report
            finally:
                native.shutdown_plane()

        results = run(_collectives_worker, num_proc=3, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_tf.so unavailable in workers")
        total = 1 + 2 + 3
        exp_gather = np.concatenate(
            [np.full((r + 1, 3), r, np.int32) for r in range(3)]).tolist()
        for rep in results:
            assert rep["sum_f32"] == total
            np.testing.assert_allclose(rep["avg_f64"],
                                       np.arange(5) * (total / 3))
            assert rep["sum_bf16"] == total
            assert rep["sum_f16"] == total
            assert rep["sum_f16_subnormal"] == 3 * 2.0 ** -15
            assert rep["gathered"] == exp_gather
            assert rep["bcast"] == 10.0
            np.testing.assert_allclose(
                rep["steps"], [np.mean([r + i for r in range(3)]) + 3
                               for i in range(4)])

    def test_distributed_optimizer_uses_native_route(self):
        def _optimizer_worker():
            import os
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.tensorflow as hvd
            from horovod_tpu.tensorflow import native

            hvd.init()
            if not native.available():
                hvd.shutdown()
                return "unavailable"
            r = int(os.environ["HVD_PROCESS_ID"])
            v = tf.Variable([2.0, 4.0])
            opt = hvd.DistributedOptimizer(
                __import__("keras").optimizers.SGD(1.0))
            core_calls = []
            orig = hvd._core.allreduce_async

            def spy(t, **kw):
                core_calls.append(kw.get("name"))
                return orig(t, **kw)

            hvd._core.allreduce_async = spy

            @tf.function
            def step():
                g = tf.constant([1.0, 1.0]) * float(r + 1)
                opt.apply_gradients([(g, v)])
                return v

            out = np.asarray(step())
            hvd._core.allreduce_async = orig
            native_used = native._state["plane_up"]
            hvd.shutdown()
            return out.tolist(), len(core_calls), bool(native_used)

        results = run(_optimizer_worker, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_tf.so unavailable in workers")
        for vals, n_core_calls, native_used in results:
            # v - lr * mean_grad = [2,4] - 1.0*[1.5,1.5]
            np.testing.assert_allclose(vals, [0.5, 2.5])
            assert native_used, "native plane did not come up"
            # the whole step stayed in-graph: the eager core saw nothing
            assert n_core_calls == 0

    def test_mismatched_submission_errors_cleanly(self):
        """Same tensor name submitted with different sizes across ranks:
        the coordinator must surface an error on every rank (reference
        ConstructResponse error checking, operations.cc:198-400) — and
        the plane must survive for subsequent correct collectives."""
        def worker():
            import os
            import numpy as np
            import tensorflow as tf
            from horovod_tpu.tensorflow import native

            rank = int(os.environ["HVD_PROCESS_ID"])
            size = int(os.environ["HVD_NUM_PROC"])
            if not native.available():
                return "unavailable"
            assert native.ensure_plane(rank, size)
            try:
                got_error = False
                try:
                    native.allreduce(tf.zeros([4 + rank]), name="clash")
                except tf.errors.OpError as e:
                    got_error = "mismatched" in str(e)
                avg_error = False
                try:
                    native.allreduce(tf.zeros([4]), average=rank == 0,
                                     name="clash.avg")
                except tf.errors.OpError as e:
                    avg_error = "mismatched" in str(e)
                root_error = False
                try:
                    native.broadcast(tf.zeros([4]), root_rank=5,
                                     name="clash.root")
                except tf.errors.OpError as e:
                    root_error = "out of range" in str(e)
                # the plane survives: a well-formed collective still works
                out = native.allreduce(tf.fill([8], float(rank + 1)),
                                       average=False, name="after")
                return (got_error, avg_error, root_error,
                        float(out.numpy()[0]))
            finally:
                native.shutdown_plane()

        results = run(worker, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_tf.so unavailable in workers")
        for got_error, avg_error, root_error, after in results:
            assert got_error, "size mismatch did not raise"
            assert avg_error, "average-mode mismatch did not raise"
            assert root_error, "out-of-range root did not raise"
            assert after == 3.0

    def test_broadcast_shape_mismatch_errors(self):
        """Same byte count, different shapes ([2,3] vs [3,2]): the shape
        digest in the READY payload must surface an error instead of
        silently delivering reinterpreted data (the reference errors on
        shape mismatch in ConstructResponse)."""
        def worker():
            import os
            import tensorflow as tf
            from horovod_tpu.tensorflow import native

            rank = int(os.environ["HVD_PROCESS_ID"])
            size = int(os.environ["HVD_NUM_PROC"])
            if not native.available():
                return "unavailable"
            assert native.ensure_plane(rank, size)
            try:
                bcast_err = False
                try:
                    t = tf.zeros([2, 3] if rank == 0 else [3, 2])
                    native.broadcast(t, root_rank=0, name="shape.clash")
                except tf.errors.OpError as e:
                    bcast_err = "mismatched" in str(e)
                ar_err = False
                try:
                    t = tf.zeros([6] if rank == 0 else [2, 3])
                    native.allreduce(t, name="shape.clash.ar")
                except tf.errors.OpError as e:
                    ar_err = "mismatched" in str(e)
                # allgather: dim0 may differ, inner dims may NOT — equal
                # row bytes with different inner shapes must be rejected
                ag_err = False
                try:
                    t = tf.zeros([2, 2, 3] if rank == 0 else [4, 3, 2])
                    native.allgather(t, name="shape.clash.ag")
                except tf.errors.OpError as e:
                    ag_err = "mismatched" in str(e)
                # matching shapes still work after the rejected ones
                out = native.broadcast(tf.fill([2, 2], float(rank + 1)),
                                       root_rank=1, name="shape.ok")
                return bcast_err, ar_err, ag_err, float(out.numpy()[0][0])
            finally:
                native.shutdown_plane()

        results = run(worker, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_tf.so unavailable in workers")
        for bcast_err, ar_err, ag_err, ok_val in results:
            assert bcast_err, "broadcast shape mismatch did not raise"
            assert ar_err, "allreduce shape mismatch did not raise"
            assert ag_err, "allgather inner-shape mismatch did not raise"
            assert ok_val == 2.0

    def test_custom_compressor_rides_pyfunc_route(self):
        """A custom Compressor (compress/decompress overridden, no
        wire_dtype) cannot be re-expressed in-graph: the fused route must
        fall back to the py_function path where the eager core applies it
        — not silently skip compression on the native plane."""
        def worker():
            import os
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.tensorflow as hvd
            from horovod_tpu.tensorflow import native
            from horovod_tpu.ops.compression import Compressor

            hvd.init()
            if not native.available():
                hvd.shutdown()
                return "unavailable"
            r = int(os.environ["HVD_PROCESS_ID"])

            class Spy(Compressor):
                calls = []

                @classmethod
                def compress(cls, tensor):
                    cls.calls.append("c")
                    return tensor, None

                @classmethod
                def decompress(cls, tensor, ctx):
                    return tensor

            v = tf.Variable([2.0, 4.0])
            opt = hvd.DistributedOptimizer(
                __import__("keras").optimizers.SGD(1.0), compression=Spy)

            @tf.function
            def step():
                g = tf.constant([1.0, 1.0]) * float(r + 1)
                opt.apply_gradients([(g, v)])
                return v

            out = np.asarray(step())
            # the custom compressor must not pay the native bootstrap it
            # cannot use: the plane stays down on this route entirely
            plane_up = native._state["plane_up"]
            hvd.shutdown()
            return out.tolist(), len(Spy.calls), bool(plane_up)

        results = run(worker, num_proc=2, env=_ENV)
        if results[0] == "unavailable":
            pytest.skip("libhvd_tf.so unavailable in workers")
        for vals, n_compress_calls, plane_up in results:
            np.testing.assert_allclose(vals, [0.5, 2.5])
            assert n_compress_calls > 0, \
                "custom compressor was skipped on the native route"
            assert not plane_up, \
                "native plane bootstrapped for a route that cannot use it"

    def test_absent_rank_falls_back_to_pyfunc_everywhere(self):
        """A rank that cannot run the native plane (HVD_TF_NATIVE=0) must
        not hang the others: their plane init times out and BOTH ranks
        train through the py_function route with correct averaging."""
        def worker():
            import os
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.tensorflow as hvd
            from horovod_tpu.tensorflow import native

            r = int(os.environ["HVD_PROCESS_ID"])
            if r == 1:
                os.environ["HVD_TF_NATIVE"] = "0"
            os.environ["HVD_TF_NATIVE_TIMEOUT"] = "3"
            hvd.init()
            v = tf.Variable([2.0, 4.0])
            opt = hvd.DistributedOptimizer(
                __import__("keras").optimizers.SGD(1.0))

            @tf.function
            def step():
                g = tf.constant([1.0, 1.0]) * float(r + 1)
                opt.apply_gradients([(g, v)])
                return v

            out = np.asarray(step())
            native_used = native._state["plane_up"]
            hvd.shutdown()
            return out.tolist(), bool(native_used)

        results = run(worker, num_proc=2, env=_ENV)
        for vals, native_used in results:
            np.testing.assert_allclose(vals, [0.5, 2.5])
            assert not native_used

    def test_gradient_tape_in_tf_function(self):
        """DistributedGradientTape inside tf.function rides the fused
        in-graph route (native or py_function) — both ranks see the
        averaged gradient."""
        def _tape_graph_worker():
            import os
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.tensorflow as hvd

            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            v = tf.Variable([3.0, 5.0])

            @tf.function
            def grads():
                with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
                    loss = tf.reduce_sum(v * float(r + 1))
                return tape.gradient(loss, [v])[0]

            g = np.asarray(grads())
            hvd.shutdown()
            return g.tolist()

        results = run(_tape_graph_worker, num_proc=2, env=_ENV)
        for g in results:
            np.testing.assert_allclose(g, [1.5, 1.5])
