"""The concurrency pass tested: the three historical race classes as
named regression fixtures (each pre-fix shape must be flagged; each
fixed shape must lint clean), the locked-accessor fixes' unit tests,
and the runtime lockdep sanitizer's detection + escalation contract
(docs/concurrency.md).

Fixture snippets are written to tmp_path and scanned with
``analyze_paths(..., program_pass=run_pass)`` — the exact invocation
``python -m tools.hvdlint --concurrency`` makes. Lock ranks for
fixtures come from per-file ``# lock_rank:`` comments, the same escape
hatch a module outside common/concurrency.py's table would use.
"""

import glob
import json
import os
import textwrap
import threading

import pytest

from tools.hvdlint import analyze_paths
from tools.hvdlint.concurrency import run_pass, selftest


def lint_concurrency(tmp_path, source, name="snippet.py"):
    """Write one fixture file and run only the concurrency pass on it."""
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    findings, _ = analyze_paths([str(f)], rules={}, program_pass=run_pass)
    return findings


def live(findings, rule=None):
    return [f for f in findings if not f.suppressed and
            (rule is None or f.rule == rule)]


# ---------------------------------------------------------------------------
# historical race fixture 1: the metrics-registry reset() self-deadlock.
# Pre-fix shape: reset() held the module singleton lock and called the
# factory, which re-acquires the same non-reentrant lock — a guaranteed
# hang the chaos drill caught dynamically. HVD022 flags it statically.
# ---------------------------------------------------------------------------

METRICS_RESET_PRE_FIX = """\
    import threading

    _registry = None  # guarded_by: _registry_lock
    _registry_lock = threading.Lock()

    def get_registry():
        global _registry
        with _registry_lock:
            if _registry is None:
                _registry = object()
            return _registry

    def reset():
        global _registry
        with _registry_lock:
            _registry = None
            return get_registry()
    """


def test_fixture_metrics_reset_self_deadlock_flagged(tmp_path):
    found = live(lint_concurrency(tmp_path, METRICS_RESET_PRE_FIX),
                 "HVD022")
    assert found, "pre-fix reset() shape must raise HVD022"
    assert any("self-deadlock" in f.message for f in found)
    assert any("get_registry" in f.message for f in found)


def test_fixture_metrics_reset_fixed_shape_clean(tmp_path):
    # the fix: drop the lock before re-entering the factory (exactly
    # what horovod_tpu/utils/metrics.py reset() does today)
    found = lint_concurrency(tmp_path, """\
        import threading

        _registry = None  # guarded_by: _registry_lock
        _registry_lock = threading.Lock()

        def get_registry():
            global _registry
            with _registry_lock:
                if _registry is None:
                    _registry = object()
                return _registry

        def reset():
            global _registry
            with _registry_lock:
                _registry = None
            return get_registry()
        """)
    assert live(found) == []


# ---------------------------------------------------------------------------
# historical race fixture 2: the shm_ring lost-wake. Pre-fix shape: the
# producer raised the ready flag OUTSIDE the lock that orders it with
# the consumer's check — the consumer could read stale False and sleep
# through the wake. HVD021 flags both off-lock touches, and names the
# consumer's thread entry.
# ---------------------------------------------------------------------------

SHM_RING_PRE_FIX = """\
    import threading

    class ShmRing:
        def __init__(self):
            self._lock = threading.Lock()
            self._ready = False  # guarded_by: _lock
            self._slots = []     # guarded_by: _lock
            self._thread = threading.Thread(target=self._consume,
                                            daemon=True)
            self._thread.start()

        def push(self, item):
            with self._lock:
                self._slots.append(item)
            self._ready = True

        def _consume(self):
            while True:
                if self._ready:
                    with self._lock:
                        self._slots.clear()
    """


def test_fixture_shm_ring_lost_wake_flagged(tmp_path):
    found = live(lint_concurrency(tmp_path, SHM_RING_PRE_FIX), "HVD021")
    msgs = [f.message for f in found]
    assert any("written off-lock" in m and "_ready" in m for m in msgs), \
        "producer's off-lock flag write must be flagged"
    assert any("read off-lock" in m and "_ready" in m for m in msgs), \
        "consumer's off-lock flag check must be flagged"
    # the consumer finding must name its thread entry — that is what
    # makes the report actionable
    assert any("thread entry 'ShmRing._consume'" in m for m in msgs)


def test_fixture_shm_ring_fixed_shape_clean(tmp_path):
    found = lint_concurrency(tmp_path, """\
        import threading

        class ShmRing:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = False  # guarded_by: _lock
                self._slots = []     # guarded_by: _lock
                self._thread = threading.Thread(target=self._consume,
                                                daemon=True)
                self._thread.start()

            def push(self, item):
                with self._lock:
                    self._slots.append(item)
                    self._ready = True

            def _consume(self):
                while True:
                    with self._lock:
                        if self._ready:
                            self._slots.clear()
        """)
    assert live(found) == []


# ---------------------------------------------------------------------------
# historical race fixture 3: the fleet poll/GC TOCTOU. Pre-fix shape:
# the subscriber's poller read the publication pointer off-lock while
# the retention-GC thread unlinked it — the poller then opened a
# directory that no longer existed. HVD021 flags the off-lock read.
# ---------------------------------------------------------------------------

FLEET_POLL_PRE_FIX = """\
    import threading

    class Publisher:
        def __init__(self):
            self._lock = threading.Lock()
            self._latest = None  # guarded_by: _lock
            self._gc = threading.Thread(target=self._gc_loop, daemon=True)
            self._gc.start()

        def publish(self, path):
            with self._lock:
                self._latest = path

        def poll(self):
            return self._latest

        def _gc_loop(self):
            with self._lock:
                self._latest = None
    """


def test_fixture_fleet_poll_gc_toctou_flagged(tmp_path):
    found = live(lint_concurrency(tmp_path, FLEET_POLL_PRE_FIX), "HVD021")
    assert any("_latest" in f.message and "read off-lock" in f.message
               for f in found), \
        "the poller's off-lock pointer read must be flagged"


def test_fixture_fleet_poll_fixed_shape_clean(tmp_path):
    found = lint_concurrency(tmp_path, """\
        import threading

        class Publisher:
            def __init__(self):
                self._lock = threading.Lock()
                self._latest = None  # guarded_by: _lock
                self._gc = threading.Thread(target=self._gc_loop,
                                            daemon=True)
                self._gc.start()

            def publish(self, path):
                with self._lock:
                    self._latest = path

            def poll(self):
                with self._lock:
                    return self._latest

            def _gc_loop(self):
                with self._lock:
                    self._latest = None
        """)
    assert live(found) == []


# ---------------------------------------------------------------------------
# HVD022 rank inversion + pass-level suppression mechanics
# ---------------------------------------------------------------------------

def test_hvd022_rank_inversion_from_lock_rank_comments(tmp_path):
    found = live(lint_concurrency(tmp_path, """\
        import threading

        # lock_rank: Box._outer = 10
        # lock_rank: Box._inner = 20

        class Box:
            def __init__(self):
                self._outer = threading.Lock()
                self._inner = threading.Lock()

            def bad(self):
                with self._inner:
                    with self._outer:
                        pass

            def good(self):
                with self._outer:
                    with self._inner:
                        pass
        """), "HVD022")
    assert len(found) == 1
    assert "inversion" in found[0].message
    assert "'_outer' (rank 10)" in found[0].message


def test_lock_held_by_private_helper_caller_is_credited(tmp_path):
    # the RacerD-style fixpoint: a private helper whose every call site
    # holds the lock is analyzed as entered-locked — no false positive
    found = lint_concurrency(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0  # guarded_by: _lock

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self._value += 1
        """)
    assert live(found) == []


def test_concurrency_findings_honor_inline_disable(tmp_path):
    found = lint_concurrency(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0  # guarded_by: _lock

            def peek(self):
                # hvdlint: disable=HVD021(GIL-atomic int read for a monitoring endpoint)
                return self._value
        """)
    assert live(found) == []
    assert any(f.suppressed and f.rule == "HVD021" for f in found)


def test_selftest_passes():
    assert selftest() is None


# ---------------------------------------------------------------------------
# the accessor fixes from the annotation sweep (satellite: true
# positives found by the pass, each with a unit test)
# ---------------------------------------------------------------------------

def test_coordinator_snapshot_accessors_return_copies():
    """eager._remote_metrics_snapshots read svc.metrics_snapshots from
    the metrics HTTP thread without the coordinator's lock; the fix
    routes every cross-thread reader through locked accessors that
    return copies."""
    from horovod_tpu.ops.negotiation import CoordinatorService

    svc = CoordinatorService.__new__(CoordinatorService)
    svc._lock = threading.Lock()
    svc.metrics_snapshots = {1: {"m": 1}}
    svc.load_snapshots = {1: {"q": 2}}
    svc.flight_dumps = {1: {"spans": []}}

    m = svc.metrics_snapshot_view()
    assert m == {1: {"m": 1}}
    m[2] = {}  # a copy: mutating the view must not touch the ledger
    assert 2 not in svc.metrics_snapshots
    assert svc.load_snapshot_view() == {1: {"q": 2}}
    assert svc.flight_dump_view() == {1: {"spans": []}}


def test_checkpoint_close_joins_outside_the_condition(tmp_path):
    """close() used to read/join/null _thread off-lock — it now
    captures-and-clears under _cv and joins outside (joining under _cv
    would deadlock the writer's exit). Exercise a full save/close."""
    from horovod_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save({"w": [1.0, 2.0]}, step=1)
    mgr.wait()
    mgr.close()
    assert mgr._thread is None
    with pytest.raises(Exception):
        mgr.save({"w": [1.0]}, step=2)  # closed manager refuses work


# ---------------------------------------------------------------------------
# runtime lockdep sanitizer (horovod_tpu/utils/lockdep.py)
# ---------------------------------------------------------------------------

@pytest.fixture
def lockdep_on(monkeypatch):
    from horovod_tpu.utils import lockdep
    monkeypatch.setenv("HVD_LOCKDEP", "1")
    lockdep.reset()
    yield lockdep
    lockdep.reset()


def test_lockdep_off_returns_raw_lock(monkeypatch):
    from horovod_tpu.utils import lockdep
    monkeypatch.delenv("HVD_LOCKDEP", raising=False)
    raw = lockdep.lock("Anything._lock")
    assert type(raw) is type(threading.Lock()), \
        "HVD_LOCKDEP unset must yield a raw threading.Lock — zero " \
        "instrumented code on the hot path"
    rraw = lockdep.rlock("Anything._rlock")
    assert type(rraw) is type(threading.RLock())


def test_lockdep_self_deadlock_detected(lockdep_on):
    a = lockdep_on.lock("T.a")
    a.acquire()
    # the second, would-hang acquire is probed non-blocking so the test
    # itself cannot deadlock; _before_acquire runs either way
    assert a.acquire(blocking=False) is False
    a.release()
    kinds = [f["kind"] for f in lockdep_on.findings()]
    assert "self_deadlock" in kinds


def test_lockdep_reentrant_lock_not_flagged(lockdep_on):
    r = lockdep_on.rlock("T.r")
    with r:
        with r:
            pass
    assert lockdep_on.findings() == []


def test_lockdep_rank_violation_against_contract(lockdep_on):
    # real names from common/concurrency.py LOCK_RANKS: Tracer._lock is
    # rank 40, CoordinatorService._lock rank 10 — taking the control-
    # plane lock while holding an observability lock is the inversion
    inner = lockdep_on.lock("Tracer._lock")
    outer = lockdep_on.lock("CoordinatorService._lock")
    with inner:
        with outer:
            pass
    finds = [f for f in lockdep_on.findings()
             if f["kind"] == "rank_violation"]
    assert finds, "acquiring rank 10 under rank 40 must be reported"
    assert finds[0]["lock_held"] == "Tracer._lock"
    assert finds[0]["lock_acquiring"] == "CoordinatorService._lock"


def test_lockdep_order_cycle_witnessed_across_threads(lockdep_on):
    a = lockdep_on.lock("CycleTest.a")
    b = lockdep_on.lock("CycleTest.b")

    def a_then_b():
        with a:
            with b:
                pass

    t = threading.Thread(target=a_then_b, name="witness-a-then-b")
    t.start()
    t.join()
    # now the reverse order on this thread: no timing-dependent
    # deadlock needed — the witnessed A->B edge closes the cycle
    with b:
        with a:
            pass
    cycles = [f for f in lockdep_on.findings()
              if f["kind"] == "order_cycle"]
    assert len(cycles) == 1, "one cycle, not one per direction"
    c = cycles[0]
    assert {c["lock_a"], c["lock_b"]} == {"CycleTest.a", "CycleTest.b"}
    assert c["thread_a_then_b"] == "witness-a-then-b"
    assert c["stack_a_then_b"] and c["stack_b_then_a"], \
        "both witness stacks must ride the finding"


def test_lockdep_findings_dedup_and_reset(lockdep_on):
    a = lockdep_on.lock("Dedup.a")
    b = lockdep_on.lock("Dedup.b")

    def a_then_b():
        with a:
            with b:
                pass

    for _ in range(2):
        t = threading.Thread(target=a_then_b)
        t.start()
        t.join()
        with b:
            with a:
                pass
    assert len(lockdep_on.findings()) == 1
    lockdep_on.reset()
    assert lockdep_on.findings() == []


def test_lockdep_hold_while_blocking(lockdep_on, monkeypatch):
    monkeypatch.setenv("HVD_LOCKDEP_STALL_S", "0.05")
    held = lockdep_on.lock("Stall.held")
    contended = lockdep_on.lock("Stall.contended")
    release = threading.Event()

    def hog():
        with contended:
            release.wait(5.0)

    t = threading.Thread(target=hog)
    t.start()
    while not contended.locked():
        pass
    with held:
        got = contended.acquire(blocking=True, timeout=0.2)
        if got:
            contended.release()
    release.set()
    t.join()
    # a caller-supplied timeout bypasses the stall probe — re-run with
    # a plain blocking acquire to hit it
    t = threading.Thread(target=hog)
    release.clear()
    t.start()
    while not contended.locked():
        pass

    def unblock():
        release.set()

    timer = threading.Timer(0.15, unblock)
    timer.start()
    with held:
        with contended:
            pass
    t.join()
    stalls = [f for f in lockdep_on.findings()
              if f["kind"] == "hold_while_blocking"]
    assert stalls, "blocking >stall_s while holding a lock must report"
    assert stalls[0]["lock_blocked_on"] == "Stall.contended"
    assert "Stall.held" in stalls[0]["locks_held"]


# ---------------------------------------------------------------------------
# the synthetic two-lock inversion drill: a witnessed inversion must
# escalate through event -> warning -> flight dump, and hvd_postmortem
# must name BOTH locks in its verdict from the dump alone.
# ---------------------------------------------------------------------------

def test_lockdep_inversion_flight_dump_names_both_locks(
        lockdep_on, monkeypatch, tmp_path):
    from horovod_tpu.utils import metrics as hvd_metrics
    from horovod_tpu.utils import tracing as hvd_tracing

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    hvd_metrics.reset(enabled=True)
    hvd_tracing.reset(enabled=True, rank=0)
    try:
        a = lockdep_on.lock("Drill.a")
        b = lockdep_on.lock("Drill.b")

        def a_then_b():
            with a:
                with b:
                    pass

        t = threading.Thread(target=a_then_b, name="drill-forward")
        t.start()
        t.join()
        with b:
            with a:
                pass

        dumps = glob.glob(os.path.join(str(tmp_path), "flight-rank*.json"))
        assert dumps, "the inversion must write a flight dump"
        with open(dumps[0]) as f:
            dump = json.load(f)
        assert dump["reason"] == "lockdep_order_cycle"
        evs = [e for e in dump.get("events", [])
               if e.get("event") == "lockdep_order_cycle"]
        assert evs, "the dump's event ring must carry the finding"
        assert {evs[0]["lock_a"], evs[0]["lock_b"]} == \
            {"Drill.a", "Drill.b"}
        assert evs[0]["stack_a_then_b"] and evs[0]["stack_b_then_a"]

        # postmortem end-to-end: the verdict names both locks + threads
        import tools.hvd_postmortem as pm
        loaded, bad = pm.load_dumps(dumps)
        assert not bad
        pm.rebase(loaded)
        verdict = pm.analyze(loaded)
        assert verdict["lockdep_findings"]
        reason = "\n".join(verdict["reasons"])
        assert "Drill.a" in reason and "Drill.b" in reason
        assert "drill-forward" in reason
    finally:
        hvd_metrics.reset()
        hvd_tracing.reset()


def test_lockdep_finding_cap(monkeypatch):
    from horovod_tpu.utils import lockdep
    monkeypatch.setenv("HVD_LOCKDEP", "1")
    monkeypatch.setenv("HVD_LOCKDEP_MAX_FINDINGS", "2")
    lockdep.reset()
    try:
        for i in range(5):
            li = lockdep.lock(f"Cap.lock{i}")
            li.acquire()
            li.acquire(blocking=False)
            li.release()
        assert len(lockdep.findings()) == 2
    finally:
        lockdep.reset()
