"""Perf-regression ledger (tools/hvd_perf.py): history ingestion in
both schemas, context-gated comparisons, noise bands, and the gate
tripping on a synthetic 10% slowdown."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                "tools"))

import hvd_perf  # noqa: E402

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _parsed(value=2350.0, value_pm=None, tokens=119000.0, mfu=0.62,
            ms=137.5, ms_pm=None, batch=16, model="gpt2-small-tpu-flash",
            **extra):
    lm = {"model": model, "tokens_per_sec_per_chip": tokens, "mfu": mfu,
          "seq_len": 1024, "batch_per_chip": batch, "ms_per_step": ms}
    if ms_pm is not None:
        lm["ms_per_step_pm"] = ms_pm
    p = {"metric": "resnet50_synthetic_images_per_sec_per_chip",
         "value": value, "unit": "images/sec/chip",
         "transformer_lm": lm}
    if value_pm is not None:
        p["value_pm"] = value_pm
    p.update(extra)
    return p


def _write(tmp_path, name, parsed, n=None, wrapper=True):
    p = tmp_path / name
    doc = {"n": n, "cmd": "python bench.py", "rc": 0, "tail": "",
           "parsed": parsed} if wrapper else parsed
    p.write_text(json.dumps(doc))
    return str(p)


class TestLoading:
    def test_wrapper_and_raw_schemas(self, tmp_path):
        a = _write(tmp_path, "a.json", _parsed(), n=1)
        b = _write(tmp_path, "b.json", _parsed(), wrapper=False)
        runs = hvd_perf.load_history([a, b])
        assert len(runs) == 2
        assert runs[0].parsed["value"] == 2350.0

    def test_captured_stdout_last_json_line(self, tmp_path):
        p = tmp_path / "run.log"
        p.write_text("warmup chatter\nnot json {\n" +
                     json.dumps(_parsed(value=2400.0)) + "\n")
        (run,) = hvd_perf.load_history([str(p)])
        assert run.parsed["value"] == 2400.0

    def test_rejects_garbage(self, tmp_path):
        p = tmp_path / "x.json"
        p.write_text("{\"unrelated\": 1}")
        with pytest.raises(ValueError, match="neither"):
            hvd_perf.load_run(str(p), 0)

    def test_ordering_provenance_beats_round_number(self, tmp_path):
        old = _write(tmp_path, "z_old.json", _parsed(value=1000.0), n=3)
        new = _write(tmp_path, "a_new.json", _parsed(
            value=2000.0, provenance={"unix_ms": 5, "label": "fresh"}))
        runs = hvd_perf.load_history([new, old])
        assert [r.parsed["value"] for r in runs] == [1000.0, 2000.0]
        assert runs[-1].label == "fresh"

    def test_real_history_loads_and_passes(self):
        files = sorted(
            os.path.join(REPO, f) for f in os.listdir(REPO)
            if f.startswith("BENCH_r") and f.endswith(".json"))
        assert len(files) >= 5
        assert hvd_perf.main(["--check"] + files) == 0


class TestCompare:
    def test_within_threshold_ok(self, tmp_path):
        files = [_write(tmp_path, "r1.json", _parsed(value=2350.0), n=1),
                 _write(tmp_path, "r2.json", _parsed(value=2330.0), n=2)]
        runs = hvd_perf.load_history(files)
        rows, regs = hvd_perf.compare(runs, 5.0)
        assert not regs
        by_leg = {r["leg"]: r for r in rows}
        assert by_leg["resnet50_img_per_sec_per_chip"]["status"] == "ok"
        assert by_leg["resnet50_img_per_sec_per_chip"][
            "worse_pct"] == pytest.approx(0.85, abs=0.01)

    def test_synthetic_10pct_slowdown_trips_gate(self, tmp_path):
        # copy of the real-schema history + a run 10% worse everywhere
        files = [
            _write(tmp_path, "r1.json", _parsed(), n=1),
            _write(tmp_path, "r2.json",
                   _parsed(value=2350.0 * 0.9, tokens=119000.0 * 0.9,
                           mfu=0.62 * 0.9, ms=137.5 / 0.9), n=2),
        ]
        assert hvd_perf.main(["--check"] + files) == 1
        runs = hvd_perf.load_history(files)
        _, regs = hvd_perf.compare(runs, 5.0)
        assert {r["leg"] for r in regs} == {
            "resnet50_img_per_sec_per_chip", "lm_tokens_per_sec_per_chip",
            "lm_mfu", "lm_ms_per_step"}
        assert all(r["worse_pct"] > 5.0 for r in regs)

    def test_config_change_suppresses_comparison(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _parsed(batch=8, ms=70.0), n=1),
            _write(tmp_path, "r2.json", _parsed(batch=16, ms=140.0), n=2),
        ]
        runs = hvd_perf.load_history(files)
        rows, regs = hvd_perf.compare(runs, 5.0)
        assert not regs
        by_leg = {r["leg"]: r for r in rows}
        assert by_leg["lm_ms_per_step"]["status"] == "config-changed"

    def test_noise_band_raises_threshold(self, tmp_path):
        # 4% slowdown vs a 1% threshold, but the pm half-ranges cover
        # 6% of the baseline → inside noise, no trip
        files = [
            _write(tmp_path, "r1.json",
                   _parsed(ms=100.0, ms_pm=3.0), n=1),
            _write(tmp_path, "r2.json",
                   _parsed(ms=104.0, ms_pm=3.0), n=2),
        ]
        runs = hvd_perf.load_history(files)
        rows, regs = hvd_perf.compare(runs, 1.0)
        assert not regs
        by_leg = {r["leg"]: r for r in rows}
        assert by_leg["lm_ms_per_step"]["noise_pct"] == pytest.approx(6.0)
        assert by_leg["lm_ms_per_step"]["status"] == "ok"

    def test_new_leg_never_trips(self, tmp_path):
        base = _parsed()
        withserve = _parsed(serve={"speedup_tokens_per_step": 1.99})
        files = [_write(tmp_path, "r1.json", base, n=1),
                 _write(tmp_path, "r2.json", withserve, n=2)]
        runs = hvd_perf.load_history(files)
        rows, regs = hvd_perf.compare(runs, 5.0)
        assert not regs
        by_leg = {r["leg"]: r for r in rows}
        assert by_leg["serve_speedup"]["status"] == "new"

    def test_skips_runs_missing_the_leg(self, tmp_path):
        # leg compares against the most recent run that HAS it
        no_lm = {"metric": "resnet50_synthetic_images_per_sec_per_chip",
                 "value": 2340.0, "unit": "images/sec/chip"}
        files = [
            _write(tmp_path, "r1.json", _parsed(tokens=120000.0), n=1),
            _write(tmp_path, "r2.json", no_lm, n=2),
            _write(tmp_path, "r3.json", _parsed(tokens=100000.0), n=3),
        ]
        runs = hvd_perf.load_history(files)
        _, regs = hvd_perf.compare(runs, 5.0)
        assert "lm_tokens_per_sec_per_chip" in {r["leg"] for r in regs}


class TestCLI:
    def test_report_renders(self, tmp_path, capsys):
        files = [_write(tmp_path, "r1.json", _parsed(), n=1),
                 _write(tmp_path, "r2.json", _parsed(value=2360.0), n=2)]
        assert hvd_perf.main(["--report"] + files) == 0
        out = capsys.readouterr().out
        assert "resnet50_img_per_sec_per_chip" in out
        assert "latest run" in out

    def test_json_output(self, tmp_path, capsys):
        files = [_write(tmp_path, "r1.json", _parsed(), n=1),
                 _write(tmp_path, "r2.json",
                        _parsed(value=2000.0), n=2)]
        assert hvd_perf.main(["--json", "--check"] + files) == 1
        doc = json.loads(capsys.readouterr().out)
        assert "resnet50_img_per_sec_per_chip" in doc["regressions"]
        assert len(doc["runs"]) == 2

    def test_missing_file_exits_2(self, capsys):
        assert hvd_perf.main(["--check", "/nonexistent/x.json"]) == 2
        assert "hvd_perf" in capsys.readouterr().err

    def test_threshold_flag(self, tmp_path):
        files = [_write(tmp_path, "r1.json", _parsed(value=2000.0), n=1),
                 _write(tmp_path, "r2.json", _parsed(value=1940.0), n=2)]
        assert hvd_perf.main(["--check", "--threshold", "2"] + files) == 1
        assert hvd_perf.main(["--check", "--threshold", "10"] + files) == 0
