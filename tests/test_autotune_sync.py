"""Multi-process autotune: process 0 tunes and the other processes adopt
the tuned fusion-threshold/cycle-time (the reference coordinator's
parameter broadcast, parameter_manager.cc:66-81). Under rank-0
negotiation the values ride every CycleResponse; in the non-negotiated
fallback they sync via the count-scheduled allgather
(_sync_tuned_params, HOROVOD_AUTOTUNE_SYNC_COLLECTIVES) the TestSyncUnit
cases exercise."""

import numpy as np

from horovod_tpu.run.launch import run

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "HOROVOD_AUTOTUNE": "1",
    "HOROVOD_AUTOTUNE_SYNC_COLLECTIVES": "4",
}


class TestAutotuneSync:
    def test_processes_adopt_identical_tuned_params(self):
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            # one suggestion per flush cycle, so tuning definitely moves
            # the knobs within a short run
            from horovod_tpu.utils import autotune as at
            at.CYCLES_PER_SAMPLE = 1
            at.SAMPLES_PER_STEP = 1
            hvd.init()
            for i in range(9):
                hvd.allreduce(np.ones((4,), np.float32), name=f"t{i}",
                              average=False)
            from horovod_tpu.common import state
            cfg = state.global_state().config
            out = (int(cfg.fusion_threshold),
                   round(float(cfg.cycle_time_ms), 3))
            hvd.shutdown()
            return out

        results = run(fn, num_proc=2, env=_ENV)
        # every process adopted tuned (non-default) values: rank 0 tunes,
        # the others mirror the knobs off the coordinator's responses.
        # Exact equality across processes is not asserted — a worker's
        # mirror is as fresh as its last applied response, and rank 0 may
        # have staged a newer suggestion since (the reference has the
        # same propagation lag between coordinator tune steps and worker
        # parameter updates, parameter_manager.cc:66-81).
        default = (64 * 1024 * 1024, 5.0)
        for res in results:
            assert res != default, results

    def test_results_stay_correct_while_tuning(self):
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.utils import autotune as at
            at.CYCLES_PER_SAMPLE = 1
            at.SAMPLES_PER_STEP = 1
            hvd.init()
            vals = []
            for i in range(10):
                out = hvd.allreduce(np.full((3,), float(i), np.float32),
                                    average=False, name=f"v{i}")
                vals.append(float(np.asarray(out)[0]))
            hvd.shutdown()
            return vals

        results = run(fn, num_proc=2, env=_ENV)
        want = [2.0 * i for i in range(10)]
        assert results[0] == want and results[1] == want, results


class TestSyncUnit:
    def test_sync_applies_row0(self, hvd):
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        coord._proposed_params = (123456.0, 7.5)
        coord._sync_tuned_params()
        cfg = horovod_tpu.common.state.global_state().config
        assert cfg.fusion_threshold == 123456
        assert cfg.cycle_time_ms == 7.5
        assert coord._proposed_params is None

    def test_sync_roundtrips_large_threshold(self, hvd):
        # thresholds >= 2 GiB must survive the int32 wire format exactly
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        coord._proposed_params = (float(3 * 1024 ** 3 + 12345), 2.0)
        coord._sync_tuned_params()
        cfg = horovod_tpu.common.state.global_state().config
        assert cfg.fusion_threshold == 3 * 1024 ** 3 + 12345

    def test_sync_clears_pending_adoption(self, hvd):
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        coord._proposed_params = (1024.0, 3.0)
        coord._autotune_pending_adoption = True
        coord._sync_tuned_params()
        assert coord._autotune_pending_adoption is False

    def test_sync_marks_adoption_flush(self, hvd):
        # the adoption flush must be excluded from autotune scoring
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        coord._adopted_this_flush = False
        coord._proposed_params = (2048.0, 4.0)
        coord._sync_tuned_params()
        assert coord._adopted_this_flush is True

    def test_sync_without_proposal_keeps_current(self, hvd):
        import horovod_tpu
        coord = horovod_tpu.common.state.global_state().coordinator
        cfg = horovod_tpu.common.state.global_state().config
        before = (cfg.fusion_threshold, cfg.cycle_time_ms)
        coord._sync_tuned_params()
        assert (cfg.fusion_threshold, cfg.cycle_time_ms) == before


class TestPassiveScoring:
    """Round-4 passive scorer: a cycle is scored as its batch bytes over
    the wall time to the NEXT flush — timestamps the loop already has
    (the reference ParameterManager's approach, operations.cc:1553-1555,
    no extra synchronization). Scoring must not force device syncs, and
    idle gaps between flushes must not be scored."""

    def _attach(self, seed=3):
        import horovod_tpu
        from horovod_tpu.utils import autotune as at

        state = horovod_tpu.common.state.global_state()
        coord, cfg = state.coordinator, state.config
        saved = (coord.autotuner, coord._autotune_defer,
                 coord._at_prev_flush, coord._autotune_pending_adoption)
        tuner = at.Autotuner(cfg, seed=seed)
        coord.autotuner = tuner
        coord._autotune_defer = False
        coord._at_prev_flush = None
        coord._autotune_pending_adoption = False
        calls = []
        orig = tuner.record_cycle
        tuner.record_cycle = lambda b, d: (calls.append((b, d)),
                                           orig(b, d))[1]

        def restore():
            (coord.autotuner, coord._autotune_defer,
             coord._at_prev_flush,
             coord._autotune_pending_adoption) = saved
        return coord, tuner, calls, restore

    def _burst(self, coord, hvd, tag, i):
        import numpy as np
        with coord.hold_cycle():
            h = hvd.allreduce_async(np.ones((2, 8), np.float32),
                                    average=False, name=f"{tag}.{i}")
        coord.flush()
        hvd.synchronize(h)

    def test_scores_previous_cycle_over_inter_flush_window(self, hvd):
        coord, tuner, calls, restore = self._attach()
        try:
            self._burst(coord, hvd, "pas", 0)   # seeds the window
            self._burst(coord, hvd, "pas", 1)   # scores burst 0
            assert len(calls) == 1
            nbytes, dur = calls[0]
            assert nbytes == 2 * 8 * 4
            assert 0 < dur < 1.0
        finally:
            restore()

    def test_scoring_never_blocks_on_device(self, hvd):
        import jax
        coord, tuner, calls, restore = self._attach()
        blocked = []
        orig = jax.block_until_ready
        jax.block_until_ready = lambda x: (blocked.append(1), orig(x))[1]
        try:
            self._burst(coord, hvd, "nosync", 0)
            self._burst(coord, hvd, "nosync", 1)
            assert len(calls) == 1
            assert not blocked, \
                "passive scoring must not force a device sync"
        finally:
            jax.block_until_ready = orig
            restore()

    def test_idle_gap_is_not_scored(self, hvd):
        import time
        coord, tuner, calls, restore = self._attach()
        try:
            self._burst(coord, hvd, "idle", 0)
            time.sleep(1.05)                    # > idle cap (1s default)
            self._burst(coord, hvd, "idle", 1)  # gap: skipped
            assert calls == []
            self._burst(coord, hvd, "idle", 2)  # quick: scored
            assert len(calls) == 1 and calls[0][1] < 1.0
        finally:
            restore()

    def test_window_resets_when_knobs_move(self, hvd):
        from horovod_tpu.utils import autotune as at
        saved = (at.CYCLES_PER_SAMPLE, at.SAMPLES_PER_STEP)
        at.CYCLES_PER_SAMPLE = 1
        at.SAMPLES_PER_STEP = 1
        coord, tuner, calls, restore = self._attach()
        try:
            self._burst(coord, hvd, "move", 0)
            self._burst(coord, hvd, "move", 1)  # scores + moves knobs
            assert len(calls) == 1
            # knob change restarts the window: the next flush seeds, the
            # one after scores — an interval straddling old/new knobs is
            # never attributed to either
            assert coord._at_prev_flush is None
            self._burst(coord, hvd, "move", 2)
            assert coord._at_prev_flush is not None
        finally:
            restore()
            (at.CYCLES_PER_SAMPLE, at.SAMPLES_PER_STEP) = saved


class TestFreeze:
    def test_freeze_adopts_best_and_stops_scoring(self, hvd):
        """Autotuner.freeze: the reference ParameterManager's converged
        state (tune, then run at the best values with scoring off,
        parameter_manager.cc:155-210). After freeze, record_cycle is a
        no-op and the knobs hold the best scored point."""
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.utils import autotune as at

        cfg = HorovodConfig.from_env()
        tuner = at.Autotuner(cfg, seed=1)
        # score two points directly through the engine, then freeze
        tuner._engine.record(1 << 20, 5.0, 10.0)
        tuner._engine.record(8 << 20, 7.0, 50.0)
        best = tuner.freeze()
        assert best is not None
        assert (tuner.threshold, tuner.cycle_time_ms) == (best[0], best[1])
        assert best[2] == 50.0 and tuner.threshold == 8 << 20
        # scoring is off: many cycles never advance the knobs
        for _ in range(200):
            assert tuner.record_cycle(1 << 20, 0.001) is False
        assert tuner.threshold == 8 << 20

    def test_freeze_clamps_boundary_cycle(self, hvd):
        """A best point parked at the top of CYCLE_BOUNDS_MS is a
        flat-score artifact of passive scoring (r5 adopted 99.22 ms this
        way), not a tuned value: freeze keeps the threshold but falls
        back to the pre-tune default cycle and says so."""
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.utils import autotune as at

        cfg = HorovodConfig.from_env()
        default_cycle = float(cfg.cycle_time_ms)
        tuner = at.Autotuner(cfg, seed=4)
        tuner._engine.record(8 << 20, 99.22, 50.0)  # the r5 adoption
        best = tuner.freeze()
        assert best is not None and best[1] == 99.22
        assert tuner.threshold == 8 << 20          # threshold kept
        assert tuner.cycle_time_ms == default_cycle
        assert tuner.cycle_boundary_clamped is True

        # interior points are untouched (and the flag stays down)
        tuner2 = at.Autotuner(cfg, seed=5)
        upper = (at.CYCLE_BOUNDS_MS[1]
                 - 2 * at.CYCLE_BOUNDARY_FRAC
                 * (at.CYCLE_BOUNDS_MS[1] - at.CYCLE_BOUNDS_MS[0]))
        tuner2._engine.record(4 << 20, upper, 50.0)
        tuner2.freeze()
        assert tuner2.cycle_time_ms == upper
        assert tuner2.cycle_boundary_clamped is False

    def test_coordinator_freeze_applies_config(self, hvd):
        import horovod_tpu
        from horovod_tpu.utils import autotune as at

        state = horovod_tpu.common.state.global_state()
        coord = state.coordinator
        cfg = state.config
        saved = (cfg.fusion_threshold, cfg.cycle_time_ms,
                 coord.autotuner, coord._autotune_defer)
        try:
            coord.autotuner = at.Autotuner(cfg, seed=2)
            coord._autotune_defer = False
            coord.autotuner._engine.record(4 << 20, 9.0, 42.0)
            best = coord.freeze_autotune()
            assert best is not None
            assert cfg.fusion_threshold == 4 << 20
            assert cfg.cycle_time_ms == 9.0
        finally:
            (cfg.fusion_threshold, cfg.cycle_time_ms,
             coord.autotuner, coord._autotune_defer) = saved
