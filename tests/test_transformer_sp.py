"""Transformer with sequence parallelism: shard_map('sp') forward with ring
attention must match the single-device full-attention forward."""

import numpy as np
import pytest


@pytest.mark.parametrize("impl", ["ring", "ring_flash", "ulysses"])
def test_sp_forward_matches_full(hvd, impl):
    import dataclasses
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.models import transformer as tr

    cfg = tr.TransformerConfig(vocab_size=64, num_layers=2, num_heads=8,
                               d_model=32, d_ff=64, max_seq_len=64,
                               dtype=jnp.float32, attention_impl=impl)
    model = tr.TransformerLM(cfg)
    tokens = np.random.RandomState(0).randint(0, 64, (2, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens))["params"]

    # single-device reference (full attention path)
    full_logits = model.apply({"params": params}, jnp.asarray(tokens))

    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    sp_logits = jax.jit(jax.shard_map(
        lambda p, t: model.apply({"params": p}, t),
        mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp")))(params, jnp.asarray(tokens))

    np.testing.assert_allclose(np.asarray(sp_logits),
                               np.asarray(full_logits), rtol=2e-4, atol=2e-4)


def test_sp_training_step(hvd):
    """One dp x sp training step with ring attention: loss finite, grads
    flow."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu import trainer
    from horovod_tpu.models import transformer as tr

    cfg = tr.TransformerConfig(vocab_size=64, num_layers=1, num_heads=4,
                               d_model=16, d_ff=32, max_seq_len=32,
                               dtype=jnp.float32, attention_impl="ring")
    model = tr.TransformerLM(cfg)
    tokens = np.random.RandomState(1).randint(0, 64, (4, 33)).astype(np.int32)
    # shift globally BEFORE sharding the sequence over sp
    inputs, labels = tokens[:, :-1], tokens[:, 1:]
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(inputs))["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    devices = np.asarray(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))

    def step(p, s, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            return trainer.softmax_cross_entropy(logits, y)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        grads = jax.lax.pmean(grads, ("dp", "sp"))
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s, jax.lax.pmean(
            loss, ("dp", "sp"))

    # batch sharded over dp AND sequence sharded over sp: each worker holds
    # a [2, 8] tile; ring attention runs globally over sp
    out = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("dp", "sp"), P("dp", "sp")),
        out_specs=(P(), P(), P())))(params, opt_state, jnp.asarray(inputs),
                                    jnp.asarray(labels))
    params2, _, loss = out
    assert np.isfinite(float(loss))
    changed = jax.tree_util.tree_map(
        lambda a, b: not np.allclose(np.asarray(a), np.asarray(b)),
        params, params2)
    assert any(jax.tree_util.tree_leaves(changed))


def test_full_attention_errors_on_sharded_sequence(hvd):
    """attention_impl='full' with a genuinely sp-sharded sequence must raise,
    not silently compute shard-local attention."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.models import transformer as tr

    cfg = tr.TransformerConfig(vocab_size=32, num_layers=1, num_heads=4,
                               d_model=16, d_ff=32, dtype=jnp.float32,
                               attention_impl="full")
    model = tr.TransformerLM(cfg)
    toks = np.zeros((2, 64), np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(toks))["params"]
    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    with pytest.raises(ValueError, match="sharded over the 'sp'"):
        jax.jit(jax.shard_map(
            lambda p, t: model.apply({"params": p}, t), mesh=mesh,
            in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp")))(params, jnp.asarray(toks))


def test_replicated_sequence_with_sp_bound_uses_full_path(hvd):
    """With sp bound but the sequence replicated, the model must produce the
    same result on every sp rank (no bogus global-position offsets)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.models import transformer as tr

    cfg = tr.TransformerConfig(vocab_size=32, num_layers=1, num_heads=4,
                               d_model=16, d_ff=32, dtype=jnp.float32,
                               attention_impl="ring")
    model = tr.TransformerLM(cfg)
    toks = np.random.RandomState(0).randint(0, 32, (2, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(toks))["params"]
    ref = model.apply({"params": params}, jnp.asarray(toks))
    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    # replicated input with out_specs=P(): shard_map itself verifies the
    # output is sp-invariant — if the model wrongly used axis_index('sp')
    # on replicated data this fails to trace
    out = jax.jit(jax.shard_map(
        lambda p, t: model.apply({"params": p}, t),
        mesh=mesh, in_specs=(P(), P()),
        out_specs=P()))(params, jnp.asarray(toks))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
