"""Native runtime core (libhvd_core.so) — C++ parity components
(SURVEY.md §2.1: fusion planner, response cache, tensor table/stall,
timeline writer, autotuner)."""

import ctypes
import json
import math
import os
import time

import numpy as np
import pytest

from horovod_tpu import _native

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native core not built")


def lib():
    return _native.load()


def test_version():
    assert lib().hvd_core_version().decode() == "0.1.0"


def test_plan_buckets_matches_python():
    from horovod_tpu.ops import fusion
    rng = np.random.RandomState(0)
    sizes = [int(s) for s in rng.randint(1, 10000, 64)]
    dtypes = [["float32", "bfloat16", "int32"][i % 3] for i in range(64)]
    for threshold in (0, 1, 5000, 50000, 10**9):
        native = fusion._native_plan(sizes, dtypes, threshold)
        python = fusion._python_plan(sizes, dtypes, threshold)
        assert native == python, threshold


def test_plan_buckets_lookahead_skips_oversized():
    """FuseResponses look-ahead (operations.cc:478-533): an entry that
    does not fit the open bucket is skipped — later same-dtype entries
    still join that bucket instead of being stranded in new ones."""
    from horovod_tpu.ops import fusion
    sizes = [4096, 4096, 100 << 20, 4096]
    dtypes = ["float32"] * 4
    for plan in (fusion._python_plan(sizes, dtypes, 64 << 20),
                 fusion._native_plan(sizes, dtypes, 64 << 20)):
        assert plan[0] == plan[1] == plan[3], plan  # smalls fused together
        assert plan[2] != plan[0], plan             # oversized rides alone


def test_cache_lru_eviction():
    L = lib()
    c = L.hvd_cache_create(3)
    try:
        for k in range(5):
            L.hvd_cache_insert(c, k, k * 10)
        assert L.hvd_cache_size(c) == 3
        assert L.hvd_cache_lookup(c, 0) == -1  # evicted
        assert L.hvd_cache_lookup(c, 4) == 40
        # touching 2 makes 3 the LRU
        L.hvd_cache_lookup(c, 2)
        L.hvd_cache_insert(c, 99, 990)
        assert L.hvd_cache_lookup(c, 3) == -1
        assert L.hvd_cache_lookup(c, 2) == 20
        assert L.hvd_cache_hits(c) >= 3
    finally:
        L.hvd_cache_destroy(c)


def test_table_duplicate_and_stall():
    L = lib()
    t = L.hvd_table_create()
    try:
        assert L.hvd_table_add(t, b"grad/w", 1024, 10.0) == 0
        assert L.hvd_table_add(t, b"grad/w", 1024, 10.0) == -1
        assert L.hvd_table_add(t, b"grad/b", 8, 50.0) == 0
        buf = ctypes.create_string_buffer(256)
        n = L.hvd_table_stalled(t, 80.0, 60.0, buf, 256)
        assert n == 1 and buf.value == b"grad/w"
        assert L.hvd_table_remove(t, b"grad/w") == 0
        assert L.hvd_table_count(t) == 1
    finally:
        L.hvd_table_destroy(t)


def test_native_timeline_writes_chrome_trace(tmp_path):
    from horovod_tpu.utils.timeline import NativeTimeline
    path = str(tmp_path / "trace.json")
    tl = NativeTimeline(path, mark_cycles=True)
    tl.negotiate_start("tensor_a", "allreduce")
    tl.negotiate_end("tensor_a")
    tl.start_activity("tensor_a", "ALLREDUCE")
    tl.end_activity("tensor_a")
    tl.mark_cycle_start()
    time.sleep(0.2)
    tl.close()
    data = open(path).read()
    assert "NEGOTIATE_ALLREDUCE" in data
    assert "ALLREDUCE" in data
    assert "CYCLE_START" in data
    assert "tensor_a" in data
    # well-formed JSON array
    events = json.loads(data)
    assert isinstance(events, list) and len(events) >= 5


def test_autotuner_converges_to_peak():
    """GP/EI must find the score peak in a smooth 2-D landscape
    (ParameterManager behavior)."""
    L = lib()
    t = L.hvd_autotune_create(0.0, 64e6, 1.0, 100.0, 123)
    try:
        thr, ct = ctypes.c_double(), ctypes.c_double()
        for _ in range(30):
            L.hvd_autotune_suggest(t, ctypes.byref(thr), ctypes.byref(ct))
            score = math.exp(-((thr.value - 16e6) / 20e6) ** 2 -
                             ((ct.value - 30) / 40) ** 2)
            L.hvd_autotune_record(t, thr.value, ct.value, score)
        sc = ctypes.c_double()
        assert L.hvd_autotune_best(t, ctypes.byref(thr), ctypes.byref(ct),
                                   ctypes.byref(sc))
        assert sc.value > 0.9  # near the peak (max is 1.0)
    finally:
        L.hvd_autotune_destroy(t)


def test_hash_stable():
    L = lib()
    h1 = L.hvd_hash_bytes(b"hello", 5)
    h2 = L.hvd_hash_bytes(b"hello", 5)
    h3 = L.hvd_hash_bytes(b"hellp", 5)
    assert h1 == h2 != h3


def test_autotuner_integration_with_coordinator(hvd):
    """HOROVOD_AUTOTUNE=1: the coordinator feeds cycle measurements and the
    knobs move off their defaults after enough cycles."""
    import horovod_tpu
    from horovod_tpu.common.config import HorovodConfig

    hvd.shutdown()
    cfg = HorovodConfig.from_env()
    cfg.autotune = True
    cfg.cycle_time_ms = 1.0
    hvd.init(config=cfg)
    try:
        coord = horovod_tpu.common.state.global_state().coordinator
        assert coord.autotuner is not None
        x = np.ones((8, 64), np.float32)
        # 10 cycles/sample x 5 samples/step = 50 flushes per tuning step
        for i in range(120):
            coord._paused = True
            h = hvd.allreduce_async(x, average=False, name=f"at{i}")
            coord._paused = False
            coord.flush()
            hvd.synchronize(h)
        assert coord.autotuner.best() is not None
    finally:
        hvd.shutdown()
        hvd.init()
