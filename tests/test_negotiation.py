"""Rank-0 coordinator negotiation (ops/negotiation.py — the reference's
Request/Response control plane, operations.cc:1217-1245): any-order
submission across processes, coordinator-side fusion and meta checking,
subset-stall reporting, shutdown propagation."""

import numpy as np
import pytest

from horovod_tpu.run.launch import run

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


class TestCoordinatorUnit:
    """CoordinatorService negotiation logic, no processes involved."""

    def _service(self, nproc=2, threshold=64 << 20):
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.ops import negotiation as neg
        cfg = HorovodConfig(fusion_threshold=threshold,
                            stall_warning_time_seconds=0)
        svc = neg.CoordinatorService(nproc, b"k" * 32,
                                     ports=[0], config=cfg)
        return svc, neg

    def _meta(self, neg, name, op="allreduce", dtype="float32",
              shape=(4,), root=0, average=False):
        return neg.EntryMeta(name, op, dtype, shape, root, average)

    def test_holds_until_all_ranks_submit(self):
        svc, neg = self._service()
        try:
            svc._submit(0, [self._meta(neg, "a")])
            svc._negotiate()
            assert svc._responses == []
            svc._submit(1, [self._meta(neg, "a")])
            svc._negotiate()
            assert len(svc._responses) == 1
            assert svc._responses[0].names == ["a"]
        finally:
            svc.shutdown()

    def test_fuses_ready_same_dtype_allreduces(self):
        svc, neg = self._service()
        try:
            metas = [self._meta(neg, f"g{i}") for i in range(4)] + \
                [self._meta(neg, "d", dtype="float64")] + \
                [self._meta(neg, "b", op="broadcast")]
            svc._submit(0, metas)
            svc._submit(1, metas)
            svc._negotiate()
            kinds = [(r.op, tuple(r.names)) for r in svc._responses]
            assert ("allreduce", ("g0", "g1", "g2", "g3")) in kinds
            assert ("allreduce", ("d",)) in kinds
            assert ("broadcast", ("b",)) in kinds
        finally:
            svc.shutdown()

    def test_fusion_respects_threshold(self):
        # 4-float tensors = 16 bytes each; threshold 32 → pairs
        svc, neg = self._service(threshold=32)
        try:
            metas = [self._meta(neg, f"g{i}") for i in range(4)]
            svc._submit(0, metas)
            svc._submit(1, metas)
            svc._negotiate()
            groups = [r.names for r in svc._responses]
            assert groups == [["g0", "g1"], ["g2", "g3"]]
        finally:
            svc.shutdown()

    def test_zero_threshold_disables_fusion(self):
        svc, neg = self._service(threshold=0)
        try:
            metas = [self._meta(neg, f"g{i}") for i in range(3)]
            svc._submit(0, metas)
            svc._submit(1, metas)
            svc._negotiate()
            assert [r.names for r in svc._responses] == \
                [["g0"], ["g1"], ["g2"]]
        finally:
            svc.shutdown()

    def test_meta_mismatch_becomes_error_response(self):
        svc, neg = self._service()
        try:
            svc._submit(0, [self._meta(neg, "x", shape=(2, 3))])
            svc._submit(1, [self._meta(neg, "x", shape=(2, 4))])
            svc._negotiate()
            (r,) = svc._responses
            assert r.kind == r.ERROR
            assert "x" in r.error and "ConstructResponse" in r.error
        finally:
            svc.shutdown()

    def test_response_log_pruned_after_all_ranks_ack(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._service()
        try:
            dtypes = ["float32", "float64", "int32", "int64"]  # no fusion
            for i in range(4):
                svc._submit(0, [self._meta(neg, f"t{i}", dtype=dtypes[i])])
                svc._submit(1, [self._meta(neg, f"t{i}", dtype=dtypes[i])])
            svc._negotiate()
            assert len(svc._responses) == 4
            # both ranks acknowledge seq 2 → seqs 0..2 pruned
            svc._handle(CycleRequest(0, [], ack=2), ("127.0.0.1", 0))
            svc._handle(CycleRequest(1, [], ack=2), ("127.0.0.1", 0))
            assert svc._base_seq == 3 and len(svc._responses) == 1
            # a straggler request for older seqs still gets the tail
            resp = svc._handle(CycleRequest(0, [], ack=2),
                               ("127.0.0.1", 0))
            assert resp.base_seq == 3 and len(resp.responses) == 1
        finally:
            svc.shutdown()

    def test_allgather_first_dim_may_differ(self):
        svc, neg = self._service()
        try:
            svc._submit(0, [self._meta(neg, "g", op="allgather",
                                       shape=(2, 3))])
            svc._submit(1, [self._meta(neg, "g", op="allgather",
                                       shape=(5, 3))])
            svc._negotiate()
            (r,) = svc._responses
            assert r.kind == r.EXECUTE
        finally:
            svc.shutdown()

    def _quant_service(self):
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.ops import negotiation as neg
        cfg = HorovodConfig(fusion_threshold=64 << 20,
                            stall_warning_time_seconds=0,
                            compression="int8", quant_min_bytes=1024)
        svc = neg.CoordinatorService(2, b"k" * 32, ports=[0], config=cfg)
        return svc, neg

    def test_negotiated_plan_carries_per_tensor_codec(self):
        svc, neg = self._quant_service()
        try:
            metas = [self._meta(neg, "big", shape=(1024,)),
                     self._meta(neg, "small", shape=(4,)),
                     self._meta(neg, "ints", dtype="int32",
                                shape=(1024,))]
            svc._submit(0, metas)
            svc._submit(1, metas)
            svc._negotiate()
            by_names = {tuple(r.names): r for r in svc._responses}
            # big float tensor rides the quantized wire
            assert by_names[("big",)].codec == "int8"
            # under quant_min_bytes: the encode overhead isn't worth it
            assert by_names[("small",)].codec is None
            # integer reductions are exact already; never quantized
            assert by_names[("ints",)].codec is None
        finally:
            svc.shutdown()

    def test_codec_splits_fusion_buckets(self):
        # same dtype, same average — but only one clears the size gate,
        # so they must NOT share a fused bucket (one wire format per
        # fusion buffer)
        svc, neg = self._quant_service()
        try:
            metas = [self._meta(neg, "a", shape=(1024,)),
                     self._meta(neg, "b", shape=(4,)),
                     self._meta(neg, "c", shape=(2048,))]
            svc._submit(0, metas)
            svc._submit(1, metas)
            svc._negotiate()
            plans = {tuple(r.names): getattr(r, "codec", None)
                     for r in svc._responses}
            assert plans[("a", "c")] == "int8"
            assert plans[("b",)] is None
        finally:
            svc.shutdown()

    def test_codec_fingerprint_mismatch_fails_ready_tensors(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._quant_service()
        try:
            fp0 = svc._codec_fp
            assert fp0.startswith("int8/")
            svc._handle(CycleRequest(0, [self._meta(neg, "g",
                                                    shape=(1024,))],
                                     ack=-1, codec_fp=fp0),
                        ("127.0.0.1", 0))
            svc._handle(CycleRequest(1, [self._meta(neg, "g",
                                                    shape=(1024,))],
                                     ack=-1,
                                     codec_fp="none/b256/min1024/ef1"),
                        ("127.0.0.1", 0))
            svc._negotiate()
            (r,) = svc._responses
            assert r.kind == r.ERROR
            assert "Mismatched wire-codec config" in r.error
            assert "int8" in r.error and "none" in r.error
            # the mismatch is sticky: later tensors fail too, nothing
            # ever executes under asymmetric codecs
            svc._submit(0, [self._meta(neg, "h")])
            svc._submit(1, [self._meta(neg, "h")])
            svc._negotiate()
            assert all(x.kind == x.ERROR for x in svc._responses[1:])
        finally:
            svc.shutdown()

    def test_matching_fingerprints_do_not_trip(self):
        from horovod_tpu.ops.negotiation import CycleRequest
        svc, neg = self._quant_service()
        try:
            for rank in (0, 1):
                svc._handle(CycleRequest(rank,
                                         [self._meta(neg, "g",
                                                     shape=(1024,))],
                                         ack=-1, codec_fp=svc._codec_fp),
                            ("127.0.0.1", 0))
            svc._negotiate()
            assert not svc._codec_mismatch
            (r,) = svc._responses
            assert r.kind == r.EXECUTE and r.codec == "int8"
        finally:
            svc.shutdown()


class TestResponseWire:
    """Compact CycleResponse encoding (the per-cycle hot message pickles
    via __reduce__ into versioned struct/varint bytes instead of a
    class-layout pickle; the request path's encode_hits went compact
    first)."""

    def _full_response(self, neg):
        responses = [
            neg.NegotiatedResponse(
                neg.NegotiatedResponse.EXECUTE, "allreduce",
                ["g0", "g1", "g2"], cache_ids=[0, 1, 7]),
            neg.NegotiatedResponse(
                neg.NegotiatedResponse.EXECUTE, "allreduce",
                ["q0", "q1"], codec="int8"),
            neg.NegotiatedResponse(
                neg.NegotiatedResponse.ERROR, "broadcast", ["bad"],
                error="Mismatched broadcast 'bad' across processes"),
            neg.NegotiatedResponse(
                neg.NegotiatedResponse.EXECUTE, "allgather", ["ag"]),
        ]
        return neg.CycleResponse(
            base_seq=42, responses=responses, params=(64 << 20, 5.0),
            shutdown=False, stale_ack=True, unknown_ids=(5, 9),
            lost_ranks=(3,))

    def _assert_equal(self, a, b):
        assert b.base_seq == a.base_seq
        assert b.params == a.params
        assert b.shutdown == a.shutdown
        assert b.stale_ack == a.stale_ack
        assert b.unknown_ids == a.unknown_ids
        assert b.lost_ranks == a.lost_ranks
        assert len(b.responses) == len(a.responses)
        for ra, rb in zip(a.responses, b.responses):
            assert (rb.kind, rb.op, rb.names, rb.error, rb.cache_ids,
                    rb.codec) == \
                (ra.kind, ra.op, ra.names, ra.error, ra.cache_ids,
                 ra.codec)

    def test_roundtrip_through_pickle(self):
        import cloudpickle
        from horovod_tpu.ops import negotiation as neg
        resp = self._full_response(neg)
        out = cloudpickle.loads(cloudpickle.dumps(resp))
        self._assert_equal(resp, out)

    def test_roundtrip_empty_response(self):
        import cloudpickle
        from horovod_tpu.ops import negotiation as neg
        resp = neg.CycleResponse(0, [], (0, 99.22), True)
        out = cloudpickle.loads(cloudpickle.dumps(resp))
        self._assert_equal(resp, out)

    def test_unknown_op_rides_as_string(self):
        from horovod_tpu.ops import negotiation as neg
        resp = neg.CycleResponse(1, [neg.NegotiatedResponse(
            neg.NegotiatedResponse.EXECUTE, "future_op", ["x"])],
            (1, 2.0), False)
        out = neg.decode_response(neg.encode_response(resp))
        assert out.responses[0].op == "future_op"

    def test_version_mismatch_fails_loudly(self):
        from horovod_tpu.ops import negotiation as neg
        payload = bytearray(neg.encode_response(
            self._full_response(neg)))
        payload[0] = neg.RESPONSE_WIRE_VERSION + 1
        with pytest.raises(ValueError, match="wire version"):
            neg.decode_response(bytes(payload))
        with pytest.raises(ValueError):
            neg.decode_response(b"")

    def test_compact_beats_legacy_pickle(self):
        """The point of the encoding: the steady-state message must be
        much smaller than a class-layout pickle of the same content."""
        import pickle
        from horovod_tpu.ops import negotiation as neg
        resp = self._full_response(neg)
        legacy = pickle.dumps(  # what the old wire effectively carried
            {"base_seq": resp.base_seq, "params": resp.params,
             "shutdown": resp.shutdown, "stale_ack": resp.stale_ack,
             "unknown_ids": resp.unknown_ids,
             "lost_ranks": resp.lost_ranks,
             "responses": [(r.kind, r.op, r.names, r.error, r.cache_ids)
                           for r in resp.responses]})
        assert len(neg.encode_response(resp)) < len(legacy) / 2


class TestAnyOrderSubmission:
    def test_ranks_submit_in_opposite_order(self):
        """The capability negotiation exists for (reference
        operations.cc:852-855): eager frameworks cannot guarantee
        cross-rank submission order. Without the coordinator this
        deadlocks or mismatches; with it, both complete."""
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            names = ["A", "B"] if r == 0 else ["B", "A"]
            handles = {n: hvd.allreduce_async(
                np.full((3,), 1.0 + (n == "B"), np.float32),
                average=False, name=n) for n in names}
            out = {n: float(np.asarray(hvd.synchronize(h))[0])
                   for n, h in handles.items()}
            hvd.shutdown()
            return out

        results = run(fn, num_proc=2, env=_ENV)
        for res in results:
            assert res == {"A": 2.0, "B": 4.0}, results

    def test_three_ranks_rotated_orders(self):
        """Three processes submit the same three tensors, each in a
        different rotation — the coordinator serializes them all."""
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            names = ["X", "Y", "Z"]
            order = names[r:] + names[:r]  # rotate by rank
            handles = {n: hvd.allreduce_async(
                np.full((2,), float(ord(n)), np.float32),
                average=True, name=n) for n in order}
            out = {n: float(np.asarray(hvd.synchronize(h))[0])
                   for n, h in handles.items()}
            hvd.shutdown()
            return out

        results = run(fn, num_proc=3, env=_ENV)
        want = {n: float(ord(n)) for n in "XYZ"}
        for res in results:
            assert res == want, results

    def test_burst_is_fused_by_coordinator(self):
        def fn():
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common import state
            hvd.init()
            handles = [hvd.allreduce_async(
                np.full((8,), float(i), np.float32), average=False,
                name=f"burst{i}") for i in range(6)]
            outs = [float(np.asarray(hvd.synchronize(h))[0])
                    for h in handles]
            coord = state.global_state().coordinator
            # 6 tensors completed in fewer responses than tensors →
            # the coordinator fused them
            n_responses = coord._applied_seq + 1
            hvd.shutdown()
            return outs, n_responses

        results = run(fn, num_proc=2, env=_ENV)
        for outs, n_responses in results:
            assert outs == [2.0 * i for i in range(6)]
            assert n_responses < 6, n_responses

    def test_broadcast_object_rides_the_core(self):
        def fn():
            import os
            import horovod_tpu.torch as thvd
            thvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            obj = {"epoch": 7, "blob": list(range(50))} if r == 0 else None
            out = thvd.broadcast_object(obj, root_rank=0)
            thvd.shutdown()
            return out

        results = run(fn, num_proc=2, env=_ENV)
        want = {"epoch": 7, "blob": list(range(50))}
        assert results == [want, want]


class TestNegotiatedFailure:
    def test_subset_submission_stalls_not_hangs(self):
        """A tensor only rank 0 submits must fail its synchronize with
        StalledError at the shutdown deadline (reference stall shutdown,
        operations.cc:688-786) — and the coordinator logs the missing
        ranks meanwhile."""
        def fn():
            import logging
            import os
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common import hvd_logging
            records = []

            class Capture(logging.Handler):
                def emit(self, record):
                    records.append(record.getMessage())

            hvd_logging.get_logger().addHandler(Capture())
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            # both ranks run one common collective first
            hvd.allreduce(np.ones((2,), np.float32), name="common")
            result = "none"
            if r == 0:
                try:
                    hvd.allreduce(np.ones((2,), np.float32), name="only0")
                except hvd.StalledError:
                    result = "stalled"
            else:
                import time
                time.sleep(2.5)
            warned = any("only0" in m and "missing ranks" in m
                         for m in records)
            hvd.shutdown()
            return result, (warned if r == 0 else None)

        env = dict(_ENV)
        env["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "0.5"
        env["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] = "1.5"
        results = run(fn, num_proc=2, env=env)
        assert results[0][0] == "stalled", results
        assert results[0][1] is True, results

    def test_peer_shutdown_fails_pending(self):
        """Rank 1 shuts down while rank 0 waits on a collective rank 1
        never submitted: rank 0 gets ShutdownError, not a hang
        (RequestList.shutdown → ResponseList.shutdown,
        operations.cc:1442-1478)."""
        def fn():
            import os
            import time
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            if r == 1:
                time.sleep(0.5)
                hvd.shutdown()
                return "exited"
            try:
                hvd.allreduce(np.ones((2,), np.float32), name="waiting")
                return "completed"
            except hvd.ShutdownError:
                return "shutdown"
            finally:
                hvd.shutdown()

        results = run(fn, num_proc=2, env=_ENV)
        assert results[0] == "shutdown" and results[1] == "exited", results


class TestShutdownDrain:
    """Teardown must not strand peers inside the data plane (reference
    drains outstanding responses before finalize, operations.cc:1101-1122):
    already-ordered EXECUTE work is applied by the departing rank's final
    drain cycle; work becoming ready after shutdown turns into ERROR."""

    def test_coordinator_errors_newly_ready_after_shutdown(self):
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.ops import negotiation as neg
        from horovod_tpu.ops.negotiation import CycleRequest
        cfg = HorovodConfig(stall_warning_time_seconds=0)
        svc = neg.CoordinatorService(2, b"k" * 32, ports=[0], config=cfg)
        try:
            m = neg.EntryMeta("pre", "allreduce", "float32", (4,), 0, False)
            # both ranks submit "pre"; rank 1's final request also asks
            # for shutdown — "pre" became ready IN that request, so it is
            # still EXECUTE (the drain applies it)
            svc._handle(CycleRequest(0, [m], ack=-1, req_id=1),
                        ("127.0.0.1", 0))
            resp = svc._handle(CycleRequest(1, [m], ack=-1, shutdown=True,
                                            req_id=1), ("127.0.0.1", 0))
            assert resp.shutdown
            assert [r.kind for r in resp.responses] == ["execute"]
            # work completing AFTER the shutdown flag becomes an ERROR —
            # an EXECUTE would strand the remaining rank
            m2 = neg.EntryMeta("post", "allreduce", "float32", (4,), 0,
                               False)
            svc._handle(CycleRequest(0, [m2], ack=0, req_id=2),
                        ("127.0.0.1", 0))
            resp = svc._handle(CycleRequest(1, [m2], ack=0, req_id=2),
                               ("127.0.0.1", 0))
            (err,) = resp.responses
            assert err.kind == err.ERROR and "shut down" in err.error
        finally:
            svc.shutdown()

    def test_response_log_hard_cap_marks_laggards_stale(self):
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.ops import negotiation as neg
        from horovod_tpu.ops.negotiation import CycleRequest
        cfg = HorovodConfig(fusion_threshold=0,
                            stall_warning_time_seconds=0)
        svc = neg.CoordinatorService(2, b"k" * 32, ports=[0], config=cfg)
        svc.MAX_RESPONSE_LOG = 4  # shrink the cap for the test
        try:
            # rank 1 acks nothing (crashed); rank 0 keeps submitting is
            # not enough — entries need BOTH ranks, so submit from both
            # but only advance rank 0's ack
            for i in range(8):
                m = neg.EntryMeta(f"t{i}", "allreduce", "float32", (4,),
                                  0, False)
                svc._handle(CycleRequest(0, [m], ack=i - 1, req_id=10 + i),
                            ("127.0.0.1", 0))
                svc._handle(CycleRequest(1, [m], ack=-1, req_id=10 + i),
                            ("127.0.0.1", 0))
            assert len(svc._responses) <= 4  # bounded despite no min-ack
            # the laggard's next request predates the retained window
            resp = svc._handle(CycleRequest(1, [], ack=-1, req_id=99),
                               ("127.0.0.1", 0))
            assert resp.stale_ack
            # the up-to-date rank is unaffected
            resp = svc._handle(CycleRequest(0, [], ack=7, req_id=100),
                               ("127.0.0.1", 0))
            assert not resp.stale_ack
        finally:
            svc.shutdown()

    def test_departing_rank_drains_ordered_collective(self):
        """Rank 1 pauses its background loop after announcing a tensor,
        so the EXECUTE response can only be applied by shutdown()'s final
        drain — rank 0, already blocked inside the device collective,
        must complete instead of hanging (the pre-fix behavior)."""
        def fn():
            import os
            import time
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common import state
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            coord = state.global_state().coordinator
            if r == 1:
                h = hvd.allreduce_async(np.full((2,), 2.0, np.float32),
                                        average=False, name="drained")
                time.sleep(0.5)          # announcement cycle runs
                coord._paused = True     # loop can no longer apply it
                time.sleep(1.0)          # rank 0 blocks in the collective
                hvd.shutdown()           # drain applies the EXECUTE
                return "shutdown-drained"
            time.sleep(0.8)
            h = hvd.allreduce_async(np.full((2,), 1.0, np.float32),
                                    average=False, name="drained")
            out = float(np.asarray(hvd.synchronize(h))[0])
            hvd.shutdown()
            return out

        results = run(fn, num_proc=2, env=_ENV, start_timeout_s=120.0)
        assert results[1] == "shutdown-drained"
        assert results[0] == 3.0, results


class TestPoisonGrace:
    """Control-plane loss declaration (ops/eager.py): >=3 failed cycles
    alone must NOT poison the plane — only >=3 failures sustained for
    POISON_GRACE_S (transient coordinator pauses and TCP resets at the
    5 ms cycle cadence must not tear the job down in ~15 ms)."""

    def _coordinator_with_failing_negotiator(self):
        import time as _time

        import horovod_tpu as hvd
        from horovod_tpu.common import state

        hvd.init()
        coord = state.global_state().coordinator
        coord._paused = True  # keep the background loop out of the way

        class FailingNegotiator:
            calls = 0

            def cycle(self, *a, **kw):
                FailingNegotiator.calls += 1
                raise ConnectionRefusedError("synthetic control-plane loss")

            def close(self):
                pass

        coord._negotiator = FailingNegotiator()
        return hvd, coord

    def test_three_fast_failures_do_not_poison(self):
        hvd, coord = self._coordinator_with_failing_negotiator()
        try:
            for _ in range(5):
                coord._cycle_backoff_until = 0.0  # bypass waiting
                coord._negotiated_flush_locked()
            assert coord._cycle_failures >= 3
            assert not coord._negotiation_dead, (
                "fast consecutive failures must not poison the plane "
                "before POISON_GRACE_S elapses")
            assert coord._cycle_backoff_until > 0  # backoff engaged
        finally:
            coord._negotiator = None
            hvd.shutdown()

    def test_sustained_unreachability_poisons(self):
        import time

        hvd, coord = self._coordinator_with_failing_negotiator()
        try:
            coord._cycle_backoff_until = 0.0
            coord._negotiated_flush_locked()  # first failure stamps since
            # simulate the grace window having elapsed
            coord._cycle_fail_since = (time.monotonic() -
                                       coord.POISON_GRACE_S - 1.0)
            for _ in range(3):
                coord._cycle_backoff_until = 0.0
                coord._negotiated_flush_locked()
            assert coord._negotiation_dead
        finally:
            coord._negotiator = None
            hvd.shutdown()

    def test_backoff_defers_cycles(self):
        import time

        hvd, coord = self._coordinator_with_failing_negotiator()
        try:
            coord._cycle_backoff_until = 0.0
            coord._negotiated_flush_locked()
            calls_after_first = type(coord._negotiator).calls
            # backoff window is active: the next flush must not hit the
            # negotiator at all
            coord._negotiated_flush_locked()
            assert type(coord._negotiator).calls == calls_after_first
            assert coord._cycle_backoff_until > time.monotonic() - 2.0
        finally:
            coord._negotiator = None
            hvd.shutdown()
