"""Named-mesh data plane (docs/mesh.md): HOROVOD_MESH parsing, the
process-global mesh lifecycle, spec-tree placement helpers, real
dp×tp×sp training parity against the dp-only path, cross-layout
checkpoint restore (save 2×4, restore 4×2 / 8×1, bit-exact), and the
tensor-parallel ServeEngine (temp-0 token parity + the per-chip KV
byte drop). Runs on the conftest 8-device virtual CPU mesh."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu import trainer
from horovod_tpu.models import transformer as tr
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.utils import checkpoint as ckpt
from horovod_tpu.utils import metrics as hvd_metrics

# the MULTICHIP_r05 contract: sharded vs single-path losses agree to
RTOL = 5e-4

_MESH_ENV = ("HOROVOD_MESH", "HOROVOD_MESH_TP", "HOROVOD_MESH_SP",
             "HOROVOD_MESH_PP", "HOROVOD_MESH_EP")


@pytest.fixture(autouse=True)
def _fresh_global_mesh():
    """Every test starts and ends with no committed mesh and no mesh
    env knobs — layout leakage between tests is exactly the bug
    set_global_mesh exists to make loud."""
    saved = {k: os.environ.pop(k) for k in _MESH_ENV if k in os.environ}
    mesh_lib.reset_global_mesh()
    yield
    mesh_lib.reset_global_mesh()
    os.environ.update(saved)


@pytest.fixture
def reg():
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()


def _layout(mesh):
    return {a: s for a, s in mesh.shape.items() if s > 1}


# ---------------------------------------------------------------------------
# spec parsing + env construction
# ---------------------------------------------------------------------------

class TestMeshSpec:
    def test_parse_full_spec(self):
        assert mesh_lib.parse_mesh_spec("dp=2,tp=4") == {"dp": 2, "tp": 4}
        assert mesh_lib.parse_mesh_spec(" tp=2 , sp=2 ") == \
            {"tp": 2, "sp": 2}
        assert mesh_lib.parse_mesh_spec("") == {}

    @pytest.mark.parametrize("bad", [
        "xp=2",        # unknown axis
        "tp=2,tp=4",   # duplicate
        "tp=two",      # non-int
        "tp=0",        # size < 1
        "tp",          # not axis=size
    ])
    def test_parse_fails_loud(self, bad):
        with pytest.raises(ValueError):
            mesh_lib.parse_mesh_spec(bad)

    def test_env_full_spec_wins_over_knobs(self):
        mesh = mesh_lib.mesh_from_env(environ={
            "HOROVOD_MESH": "dp=2,tp=4", "HOROVOD_MESH_TP": "2"})
        assert _layout(mesh) == {"dp": 2, "tp": 4}

    def test_env_per_axis_knobs_infer_dp(self):
        mesh = mesh_lib.mesh_from_env(
            environ={"HOROVOD_MESH_TP": "2", "HOROVOD_MESH_SP": "2"})
        assert _layout(mesh) == {"dp": 2, "tp": 2, "sp": 2}

    def test_env_empty_is_pure_dp(self):
        mesh = mesh_lib.mesh_from_env(environ={})
        assert _layout(mesh) == {"dp": jax.device_count()}

    def test_indivisible_layout_fails_loud(self):
        with pytest.raises(ValueError):
            mesh_lib.mesh_from_env(environ={"HOROVOD_MESH": "dp=3,tp=4"})


# ---------------------------------------------------------------------------
# process-global mesh lifecycle
# ---------------------------------------------------------------------------

class TestGlobalMesh:
    def test_lazy_build_commits_env_layout(self):
        assert mesh_lib.global_mesh_if_set() is None
        os.environ["HOROVOD_MESH"] = "tp=2"
        mesh = mesh_lib.global_mesh()
        assert _layout(mesh) == {"dp": 4, "tp": 2}
        # committed: later env changes don't re-build
        os.environ["HOROVOD_MESH"] = "tp=4"
        assert mesh_lib.global_mesh() is mesh
        assert mesh_lib.global_mesh_if_set() is mesh

    def test_set_is_idempotent_for_same_shape(self):
        a = mesh_lib.build_mesh(tp=2)
        mesh_lib.set_global_mesh(a)
        mesh_lib.set_global_mesh(mesh_lib.build_mesh(tp=2))  # no raise

    def test_replacing_committed_layout_raises(self):
        mesh_lib.set_global_mesh(mesh_lib.build_mesh(tp=2))
        with pytest.raises(RuntimeError):
            mesh_lib.set_global_mesh(mesh_lib.build_mesh(tp=4))
        mesh_lib.reset_global_mesh()
        mesh_lib.set_global_mesh(mesh_lib.build_mesh(tp=4))

    def test_commit_publishes_axis_gauges(self, reg):
        mesh_lib.set_global_mesh(mesh_lib.build_mesh(tp=2, sp=2))
        snap = reg.snapshot()
        fam = snap["metrics"]["hvd_mesh_axis_size"]
        sizes = {v["labels"]["axis"]: v["value"] for v in fam["values"]}
        assert sizes == {"dp": 2, "pp": 1, "tp": 2, "sp": 2, "ep": 1}


# ---------------------------------------------------------------------------
# spec-tree placement helpers
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_device_put_tree_places_by_spec(self):
        mesh = mesh_lib.build_mesh(tp=4)
        tree = {"w": jnp.ones((8, 8)), "b": jnp.ones((8,))}
        specs = {"w": P(None, "tp"), "b": P()}
        placed = mesh_lib.device_put_tree(tree, specs, mesh)
        assert placed["w"].sharding.spec == P(None, "tp")
        assert placed["w"].sharding.mesh.shape == mesh.shape
        # sharded dim: each device holds 8/4 columns
        assert placed["w"].sharding.shard_shape((8, 8)) == (8, 2)
        np.testing.assert_array_equal(np.asarray(placed["w"]),
                                      np.ones((8, 8)))

    def test_param_specs_place_tied_lm(self):
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                        attention_impl="full")
        _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
        mesh = mesh_lib.build_mesh(tp=2)
        placed = mesh_lib.device_put_tree(params, tr.param_specs(params),
                                          mesh)
        qkv = placed["layer_0"]["attn"]["qkv"]["kernel"]
        out = placed["layer_0"]["attn"]["out"]["kernel"]
        assert qkv.sharding.spec == P(None, "tp")   # column-parallel
        assert out.sharding.spec == P("tp", None)   # row-parallel

    def test_replicate_tree(self):
        mesh = mesh_lib.build_mesh(tp=2)
        placed = mesh_lib.replicate_tree({"x": jnp.arange(4.0)}, mesh)
        assert placed["x"].sharding.spec == P()

    def test_kv_cache_spec_follows_tp_divisibility(self):
        assert mesh_lib.kv_cache_spec(
            4, mesh_lib.build_mesh(tp=2)) == P(None, None, None, "tp",
                                               None)
        assert mesh_lib.kv_cache_spec(4, mesh_lib.build_mesh()) == P()
        # tp=8 doesn't divide 4 heads: replicated, never raggedly sharded
        assert mesh_lib.kv_cache_spec(4, mesh_lib.build_mesh(tp=8)) == P()

    def test_decode_head_sharding_needs_committed_tp_mesh(self):
        assert mesh_lib.decode_head_sharding(4) is None  # nothing set
        mesh_lib.set_global_mesh(mesh_lib.build_mesh(tp=2))
        hs = mesh_lib.decode_head_sharding(4)
        assert hs is not None and hs.spec == P(None, None, "tp", None)
        assert mesh_lib.decode_head_sharding(3) is None  # indivisible


# ---------------------------------------------------------------------------
# real dp×tp×sp training vs the dp-only path (MULTICHIP_r05 tolerance)
# ---------------------------------------------------------------------------

def _train_losses(mesh, sp, params, model, steps=3, batch=8, seq=32):
    loss_fn = tr.lm_loss_fn(model)
    specs = tr.param_specs(params)
    tx = optax.adam(1e-3)
    p = trainer.place(params, mesh, specs)
    opt = trainer.init_opt_state(tx, p, mesh, specs)
    step, _, batch_shard = trainer.make_gspmd_step(
        loss_fn, tx, mesh, specs, tr.batch_spec(sp=sp), donate=False,
        params=p)
    toks = np.random.RandomState(0).randint(
        0, model.cfg.vocab_size, size=(steps, batch, seq)).astype(np.int32)
    losses = []
    for t in toks:
        p, opt, loss = step(p, opt, jax.device_put(t, batch_shard))
        losses.append(float(loss))
    return losses


@pytest.mark.slow
def test_dp_tp_sp_training_matches_dp_only():
    cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                    attention_impl="full")
    model, params = tr.init_params(cfg, jax.random.PRNGKey(0))
    ref = _train_losses(mesh_lib.build_mesh(), False, params, model)
    got = _train_losses(mesh_lib.build_mesh(dp=2, tp=2, sp=2), True,
                        params, model)
    np.testing.assert_allclose(got, ref, rtol=RTOL)


@pytest.mark.slow
def test_tp2_training_matches_dp_only():
    cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                    attention_impl="full")
    model, params = tr.init_params(cfg, jax.random.PRNGKey(0))
    ref = _train_losses(mesh_lib.build_mesh(), False, params, model)
    got = _train_losses(mesh_lib.build_mesh(tp=2), False, params, model)
    np.testing.assert_allclose(got, ref, rtol=RTOL)


# ---------------------------------------------------------------------------
# cross-layout checkpoint restore
# ---------------------------------------------------------------------------

def _state_on(mesh):
    cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                    attention_impl="full")
    _, params = tr.init_params(cfg, jax.random.PRNGKey(1))
    specs = tr.param_specs(params)
    tx = optax.adam(1e-3)
    params = trainer.place(params, mesh, specs)
    opt = trainer.init_opt_state(tx, params, mesh, specs)
    return params, opt, specs, trainer.opt_state_specs(tx, params, specs)


def _assert_trees_bit_exact(got, want):
    flat_g, _ = jax.tree_util.tree_flatten_with_path(got)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want)
    assert len(flat_g) == len(flat_w)
    for (path, g), (_, w) in zip(flat_g, flat_w):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w),
            err_msg=jax.tree_util.keystr(path))


class TestCrossLayoutRestore:
    EXTRA = {"rng": [7, 11], "data_pos": 12345}

    def _save_2x4(self, tmp_path):
        mesh_a = mesh_lib.build_mesh(dp=2, tp=4)
        params, opt, specs, opt_specs = _state_on(mesh_a)
        mgr = ckpt.CheckpointManager(
            str(tmp_path), async_save=False,
            layout=mesh_lib.mesh_layout(mesh_a))
        mgr.save((params, opt), step=7, extra=dict(self.EXTRA))
        return params, opt, specs, opt_specs

    @pytest.mark.parametrize("layout", [{"dp": 4, "tp": 2}, {"dp": 8}])
    def test_save_2x4_restore_bit_exact(self, tmp_path, layout, reg):
        params, opt, specs, opt_specs = self._save_2x4(tmp_path)
        assert ckpt.saved_layout(str(tmp_path)) == \
            {"dp": 2, "pp": 1, "tp": 4, "sp": 1, "ep": 1}

        mesh_b = mesh_lib.build_mesh(**layout)
        like = jax.tree_util.tree_map(np.zeros_like, (params, opt))
        got, step, extra = ckpt.restore_on_mesh(
            str(tmp_path), like=like, spec_tree=(specs, opt_specs),
            mesh=mesh_b)
        assert step == 7
        assert extra == self.EXTRA
        _assert_trees_bit_exact(got, (params, opt))
        # every leaf landed on the restore-time mesh
        for leaf in jax.tree_util.tree_leaves(got):
            assert dict(leaf.sharding.mesh.shape) == dict(mesh_b.shape)
        # the layout change is announced on the event channel
        events = [e for e in reg.snapshot()["events"]
                  if e["event"] == "ckpt_cross_layout_restore"]
        assert len(events) == 1
        assert events[0]["saved"]["tp"] == 4
        assert events[0]["restored"] == mesh_lib.mesh_layout(mesh_b)

    def test_manager_restore_routes_spec_tree(self, tmp_path):
        params, opt, specs, opt_specs = self._save_2x4(tmp_path)
        mesh_b = mesh_lib.build_mesh(dp=4, tp=2)
        like = jax.tree_util.tree_map(np.zeros_like, (params, opt))
        mgr = ckpt.CheckpointManager(str(tmp_path), async_save=False)
        got, step, extra = mgr.restore(like=like, mesh=mesh_b,
                                       spec_tree=(specs, opt_specs))
        assert step == 7 and extra == self.EXTRA
        _assert_trees_bit_exact(got, (params, opt))

    def test_same_layout_restore_emits_no_event(self, tmp_path, reg):
        params, opt, specs, opt_specs = self._save_2x4(tmp_path)
        mesh_a = mesh_lib.build_mesh(dp=2, tp=4)
        like = jax.tree_util.tree_map(np.zeros_like, (params, opt))
        got, _, _ = ckpt.restore_on_mesh(
            str(tmp_path), like=like, spec_tree=(specs, opt_specs),
            mesh=mesh_a)
        _assert_trees_bit_exact(got, (params, opt))
        assert not [e for e in reg.snapshot()["events"]
                    if e["event"] == "ckpt_cross_layout_restore"]

    def test_legacy_unstamped_manifest_keeps_mn_path(self, tmp_path):
        # regression arm: a pre-mesh save (no layout=) restores through
        # the plain M->N path and reports no layout
        tree = {"w": jnp.arange(16.0).reshape(4, 4), "step": jnp.ones(())}
        mgr = ckpt.CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(tree, step=3, extra={"pos": 1})
        assert ckpt.saved_layout(str(tmp_path)) is None
        like = jax.tree_util.tree_map(np.zeros_like, tree)
        got, step, extra = ckpt.restore_with_extra(str(tmp_path),
                                                   like=like)
        assert step == 3 and extra == {"pos": 1}
        _assert_trees_bit_exact(got, tree)
        # ...and restore_on_mesh still works on it (placement only)
        got2, _, _ = ckpt.restore_on_mesh(
            str(tmp_path), like=like,
            spec_tree={"w": P(None, "tp"), "step": P()},
            mesh=mesh_lib.build_mesh(tp=2))
        _assert_trees_bit_exact(got2, tree)
        assert got2["w"].sharding.spec == P(None, "tp")


# ---------------------------------------------------------------------------
# tensor-parallel ServeEngine over the same mesh
# ---------------------------------------------------------------------------

def _serve_tokens(cfg, params, mesh):
    from horovod_tpu.serving.engine import ServeEngine
    from horovod_tpu.serving.queue import AdmissionQueue, Request
    engine = ServeEngine(
        cfg, params, num_slots=2, max_len=48, kv_block=8,
        queue=AdmissionQueue(max_depth=64, admission_timeout_s=1e9),
        mesh=mesh)
    prompts = [(5, 9, 17), (4, 8, 15, 16, 23, 42)]
    for i, p in enumerate(prompts):
        engine.submit(Request(f"r{i}", p, max_new_tokens=8,
                              temperature=0.0))
    results = {r.request_id: list(r.tokens)
               for r in engine.run_to_completion()}
    return [results[f"r{i}"] for i in range(len(prompts))], engine


@pytest.mark.slow
def test_tp_engine_token_parity_and_kv_bytes(reg):
    cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                    attention_impl="full")
    _, params = tr.init_params(cfg, jax.random.PRNGKey(0))

    # unsharded reference first (no committed mesh -> dp-only program)
    ref_tokens, ref_engine = _serve_tokens(cfg, params, mesh=None)

    mesh = mesh_lib.build_mesh(tp=2)
    mesh_lib.set_global_mesh(mesh)  # decode head-sharding hint
    tp_tokens, tp_engine = _serve_tokens(cfg, params, mesh=mesh)

    assert tp_tokens == ref_tokens  # temp-0, token for token
    # the point of tp serving: each chip holds heads/tp of the cache
    ratio = ref_engine.kv.per_chip_bytes() / tp_engine.kv.per_chip_bytes()
    assert ratio >= 1.9
    # head axis (index 3) sharded over tp (trailing Nones normalized)
    assert tuple(tp_engine.kv.k.sharding.spec)[:4] == \
        (None, None, None, "tp")
