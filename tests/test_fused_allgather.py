"""Fused allgather: the coordinator buckets ready same-dtype allgathers
into one response, executed as a single allgatherv with per-rank
displacement math (reference Response::add_allgather_response,
message.h:172; output offsets collective_operations.cc:68-134;
MPI_Allgatherv mpi_operations.cc:86-173)."""

import numpy as np
import pytest

from horovod_tpu.run.launch import run

_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


class TestCoordinatorGatherFusion:
    def _service(self, nproc=2, threshold=64 << 20):
        from horovod_tpu.common.config import HorovodConfig
        from horovod_tpu.ops import negotiation as neg
        cfg = HorovodConfig(fusion_threshold=threshold,
                            stall_warning_time_seconds=0)
        svc = neg.CoordinatorService(nproc, b"k" * 32, ports=[0],
                                     config=cfg)
        return svc, neg

    def _meta(self, neg, name, op="allgather", dtype="float32",
              shape=(4, 2)):
        return neg.EntryMeta(name, op, dtype, shape, 0, False)

    def test_same_dtype_allgathers_fuse(self):
        svc, neg = self._service()
        try:
            metas = [self._meta(neg, f"g{i}") for i in range(3)] + \
                [self._meta(neg, "idx", dtype="int32")] + \
                [self._meta(neg, "r", op="allreduce", shape=(4,))]
            svc._submit(0, metas)
            svc._submit(1, metas)
            svc._negotiate()
            kinds = [(r.op, tuple(r.names)) for r in svc._responses]
            assert ("allgather", ("g0", "g1", "g2")) in kinds
            assert ("allgather", ("idx",)) in kinds
            assert ("allreduce", ("r",)) in kinds
        finally:
            svc.shutdown()

    def test_gather_fusion_respects_threshold(self):
        # (4,2) float32 = 32 bytes; threshold 64 → pairs
        svc, neg = self._service(threshold=64)
        try:
            metas = [self._meta(neg, f"g{i}") for i in range(4)]
            svc._submit(0, metas)
            svc._submit(1, metas)
            svc._negotiate()
            assert [r.names for r in svc._responses] == \
                [["g0", "g1"], ["g2", "g3"]]
        finally:
            svc.shutdown()

    def test_ragged_first_dims_still_fuse(self):
        # allgatherv: dim0 may differ per rank, fusion must still group
        svc, neg = self._service()
        try:
            svc._submit(0, [self._meta(neg, "a", shape=(1, 2)),
                            self._meta(neg, "b", shape=(5, 2))])
            svc._submit(1, [self._meta(neg, "a", shape=(3, 2)),
                            self._meta(neg, "b", shape=(2, 2))])
            svc._negotiate()
            (r,) = svc._responses
            assert r.op == "allgather" and r.names == ["a", "b"]
        finally:
            svc.shutdown()


class TestFusedAllgatherEndToEnd:
    def test_burst_fuses_and_stays_exact(self):
        """Six float32 allgathers with per-rank ragged first dims and
        mixed inner shapes complete in fewer responses than tensors,
        with exact allgatherv results."""
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common import state
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            tensors = {}
            handles = {}
            for i in range(6):
                d0 = 1 + ((r + i) % 3)  # ragged across ranks
                inner = (2,) if i % 2 == 0 else (3, 2)
                t = np.full((d0,) + inner, 10.0 * r + i, np.float32)
                tensors[f"t{i}"] = t
                handles[f"t{i}"] = hvd.allgather_async(
                    t, name=f"t{i}", kind="replicated")
            outs = {n: np.asarray(hvd.synchronize(h))
                    for n, h in handles.items()}
            coord = state.global_state().coordinator
            n_responses = coord._applied_seq + 1
            hvd.shutdown()
            return tensors, outs, n_responses

        results = run(fn, num_proc=2, env=_ENV)
        locals_by_rank = [res[0] for res in results]
        for tensors, outs, n_responses in results:
            for i in range(6):
                want = np.concatenate(
                    [locals_by_rank[p][f"t{i}"] for p in range(2)], axis=0)
                np.testing.assert_array_equal(outs[f"t{i}"], want)
            assert n_responses < 6, n_responses  # gathers were fused

    def test_mixed_dtypes_split_buckets_exactly(self):
        """float32 values + int32 indices (the sparse pattern): two
        buckets, both exact, including a scalar member."""
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            hv = [hvd.allgather_async(
                np.full((r + 1, 2), float(10 * r + i), np.float32),
                name=f"v{i}", kind="replicated") for i in range(2)]
            hs = hvd.allgather_async(np.float32(r + 7.0), name="scalar",
                                     kind="replicated")
            hi = hvd.allgather_async(
                np.arange(r + 2, dtype=np.int32) + 100 * r,
                name="idx", kind="replicated")
            outv = [np.asarray(hvd.synchronize(h)) for h in hv]
            outs = np.asarray(hvd.synchronize(hs))
            outi = np.asarray(hvd.synchronize(hi))
            hvd.shutdown()
            return outv, outs, outi

        results = run(fn, num_proc=2, env=_ENV)
        for outv, outs, outi in results:
            for i in range(2):
                want = np.concatenate([
                    np.full((1, 2), float(i), np.float32),
                    np.full((2, 2), float(10 + i), np.float32)], axis=0)
                np.testing.assert_array_equal(outv[i], want)
            np.testing.assert_array_equal(
                outs, np.asarray([7.0, 8.0], np.float32))
            np.testing.assert_array_equal(
                outi, np.concatenate([np.arange(2, dtype=np.int32),
                                      np.arange(3, dtype=np.int32) + 100]))

    def test_grouped_sparse_allreduce_rides_fused_gathers(self):
        """The word2vec pattern: several IndexedSlices reduced with all
        gathers in flight — union semantics preserved, fewer responses
        than collectives."""
        def fn():
            import os
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.common import state
            from horovod_tpu.ops.sparse import (IndexedSlices,
                                                grouped_sparse_allreduce)
            hvd.init()
            r = int(os.environ["HVD_PROCESS_ID"])
            slices = [IndexedSlices(
                np.full((2, 3), float(r + i), np.float32),
                np.asarray([2 * r, 2 * r + 1], np.int32),
                (8, 3)) for i in range(3)]
            outs = grouped_sparse_allreduce(slices, average=True)
            coord = state.global_state().coordinator
            n_responses = coord._applied_seq + 1
            got = [(np.asarray(o.values), np.asarray(o.indices))
                   for o in outs]
            hvd.shutdown()
            return got, n_responses

        results = run(fn, num_proc=2, env=_ENV)
        for got, n_responses in results:
            for i, (vals, idx) in enumerate(got):
                want_vals = np.concatenate([
                    np.full((2, 3), float(i), np.float32),
                    np.full((2, 3), float(1 + i), np.float32)]) / 2.0
                np.testing.assert_allclose(vals, want_vals)
                np.testing.assert_array_equal(
                    idx, np.asarray([0, 1, 2, 3], np.int32))
            # 6 gathers (3 values + 3 indices) → 2 fused responses
            assert n_responses <= 3, n_responses
