"""Serving plane (horovod_tpu/serving/): scheduler join/retire
invariants, KV block-ledger accounting (no leaks, loud double-free),
admission control, SLO metric emission, and the engine end-to-end —
including temp-0 parity between the KV-cached engine and a no-cache
greedy reference over the same model."""

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp

from horovod_tpu.models import transformer as tr
from horovod_tpu.serving.kv_cache import BlockLedger, KVCache
from horovod_tpu.serving.queue import AdmissionQueue, Request
from horovod_tpu.serving.scheduler import SlotScheduler
from horovod_tpu.utils import metrics as hvd_metrics


@pytest.fixture
def reg():
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()



def _value(snap, name, **labels):
    fam = snap["metrics"].get(name)
    if fam is None:
        return None
    for v in fam["values"]:
        if all(v["labels"].get(k) == lv for k, lv in labels.items()):
            return v.get("value", v.get("count"))
    return None


def _events(snap, kind):
    return [e for e in snap["events"] if e["event"] == kind]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# SlotScheduler
# ---------------------------------------------------------------------------

class TestSlotScheduler:
    def test_join_assigns_each_slot_once(self):
        s = SlotScheduler(3)
        slots = [s.join(f"r{i}") for i in range(3)]
        assert sorted(slots) == [0, 1, 2]
        assert not s.can_join()
        with pytest.raises(RuntimeError):
            s.join("overflow")

    def test_retire_frees_for_immediate_reuse(self):
        s = SlotScheduler(2)
        a = s.join("a")
        s.join("b")
        s.retire(a)
        assert s.can_join()
        assert s.join("c") == a
        assert s.active[a] == "c"

    def test_retire_inactive_slot_raises(self):
        s = SlotScheduler(2)
        with pytest.raises(KeyError):
            s.retire(0)

    def test_continuous_joins_mid_wave(self):
        s = SlotScheduler(2, policy="continuous")
        s.join("a")
        s.begin_wave()
        assert s.can_join()  # the whole point of continuous batching

    def test_drain_blocks_joins_until_batch_empties(self):
        s = SlotScheduler(2, policy="drain")
        a = s.join("a")
        s.begin_wave()
        assert not s.can_join()  # wave started, one slot still free
        with pytest.raises(RuntimeError):
            s.join("b")
        s.retire(a)  # batch empty -> next wave may fill
        assert s.can_join()
        s.join("b")

    def test_begin_wave_on_empty_batch_is_noop(self):
        s = SlotScheduler(1, policy="drain")
        s.begin_wave()
        assert s.can_join()

    def test_rejects_bad_policy_and_size(self):
        with pytest.raises(ValueError):
            SlotScheduler(2, policy="paged")
        with pytest.raises(ValueError):
            SlotScheduler(0)


# ---------------------------------------------------------------------------
# BlockLedger
# ---------------------------------------------------------------------------

class TestBlockLedger:
    def test_alloc_grow_free_roundtrip_no_leak(self):
        led = BlockLedger(2, max_len=32, block_size=8)
        slot = led.alloc(5)
        assert slot is not None
        assert led.blocks_in_use == 1  # ceil(5/8)
        assert led.grow(slot, 9)  # crosses into block 2
        assert led.blocks_in_use == 2
        assert led.length(slot) == 9
        led.free(slot)
        assert led.blocks_in_use == 0
        assert led.free_slots == 2

    def test_budget_refuses_oversubscription(self):
        # 2 slots but budget for only 3 blocks of 8
        led = BlockLedger(2, max_len=32, block_size=8, total_blocks=3)
        a = led.alloc(16)  # 2 blocks
        assert a is not None
        assert led.can_alloc(8)
        assert not led.can_alloc(9)  # would need 2, only 1 left
        b = led.alloc(8)
        assert b is not None
        assert not led.grow(b, 9)  # grow refused at budget...
        assert led.length(b) == 8  # ...and state unchanged
        led.free(a)
        assert led.grow(b, 9)  # budget freed -> grow succeeds

    def test_grow_refuses_past_max_len(self):
        led = BlockLedger(1, max_len=16, block_size=8)
        slot = led.alloc(8)
        assert led.grow(slot, 16)
        assert not led.grow(slot, 17)

    def test_double_free_and_unknown_grow_raise(self):
        led = BlockLedger(1, max_len=16, block_size=8)
        slot = led.alloc(4)
        led.free(slot)
        with pytest.raises(KeyError):
            led.free(slot)
        with pytest.raises(KeyError):
            led.grow(slot, 8)

    def test_alloc_at_claims_specific_slot(self):
        led = BlockLedger(3, max_len=16, block_size=8)
        led.alloc_at(1, 4)
        assert led.length(1) == 4
        with pytest.raises(KeyError):
            led.alloc_at(1, 4)  # taken: scheduler/ledger desync
        with pytest.raises(KeyError):
            led.alloc_at(7, 4)  # no such slot
        led2 = BlockLedger(2, max_len=16, block_size=8, total_blocks=1)
        led2.alloc_at(0, 8)
        with pytest.raises(RuntimeError):
            led2.alloc_at(1, 8)  # over budget

    def test_kv_cache_shapes_follow_config(self):
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32)
        kv = KVCache(cfg, num_slots=3, max_len=32, block_size=8)
        head_dim = cfg.d_model // cfg.num_heads
        assert kv.k.shape == (cfg.num_layers, 3, 32, cfg.num_heads,
                              head_dim)
        assert kv.k.dtype == cfg.dtype
        assert kv.num_slots == 3


# ---------------------------------------------------------------------------
# AdmissionQueue
# ---------------------------------------------------------------------------

class TestAdmissionQueue:
    def test_rejects_loudly_when_full(self, reg):
        clock = FakeClock()
        q = AdmissionQueue(max_depth=1, admission_timeout_s=10.0,
                           clock=clock)
        assert q.submit(Request("a", (1,)))
        assert not q.submit(Request("b", (1,)))
        snap = reg.snapshot()
        assert _value(snap, "hvd_serve_requests_total",
                      outcome="rejected") == 1.0
        assert any(e["reason"] == "queue_full"
                   for e in _events(snap, "serve_reject"))

    def test_pop_rejects_deadline_expired(self, reg):
        clock = FakeClock()
        q = AdmissionQueue(max_depth=8, admission_timeout_s=5.0,
                           clock=clock)
        q.submit(Request("stale", (1,), deadline_s=1.0))
        q.submit(Request("fresh", (1,)))
        clock.t = 2.0  # past stale's own deadline, inside queue timeout
        got = q.pop()
        assert got.request_id == "fresh"
        snap = reg.snapshot()
        assert any(e["request_id"] == "stale" and
                   e["reason"] == "deadline"
                   for e in _events(snap, "serve_reject"))
        assert q.pop() is None

    def test_requeue_goes_to_head(self, reg):
        q = AdmissionQueue(max_depth=2, admission_timeout_s=10.0)
        q.submit(Request("a", (1,)))
        q.submit(Request("b", (1,)))
        first = q.pop()
        q.requeue(first)  # cache pressure: back to the head, not tail
        assert q.pop().request_id == "a"
        assert q.pop().request_id == "b"

    def test_depth_gauge_tracks_queue(self, reg):
        q = AdmissionQueue(max_depth=4, admission_timeout_s=10.0)
        q.submit(Request("a", (1,)))
        q.submit(Request("b", (1,)))
        snap = reg.snapshot()
        assert _value(snap, "hvd_serve_queue_depth") == 2.0


# ---------------------------------------------------------------------------
# ServeEngine end-to-end (CPU, tiny fp32 config)
# ---------------------------------------------------------------------------

def _tiny():
    cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                    attention_impl="full")
    _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _greedy_reference(cfg, params, prompt, n_new):
    """No-cache greedy decode: full forward over the growing sequence
    every step — the oracle the KV-cached engine must match."""
    model = tr.TransformerLM(cfg)
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply({"params": params},
                             jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def _engine(cfg, params, **kw):
    from horovod_tpu.serving.engine import ServeEngine
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 48)
    kw.setdefault("kv_block", 8)
    kw.setdefault("queue", AdmissionQueue(max_depth=64,
                                          admission_timeout_s=1e9))
    return ServeEngine(cfg, params, **kw)


class TestServeEngine:
    def test_temp0_matches_no_cache_greedy(self, reg):
        cfg, params = _tiny()
        engine = _engine(cfg, params)
        prompts = [(5, 9, 17), (4, 8, 15, 16, 23, 42)]
        for i, p in enumerate(prompts):
            engine.submit(Request(f"r{i}", p, max_new_tokens=10))
        results = {r.request_id: r
                   for r in engine.run_to_completion()}
        assert len(results) == 2
        for i, p in enumerate(prompts):
            r = results[f"r{i}"]
            assert r.outcome == "completed"
            assert list(r.tokens) == _greedy_reference(cfg, params, p, 10)
        assert engine.kv.ledger.blocks_in_use == 0
        assert engine.active_count == 0

    def test_continuous_join_mid_stream_and_no_leaks(self, reg):
        cfg, params = _tiny()
        engine = _engine(cfg, params, num_slots=2)
        engine.submit(Request("long", (1, 2, 3), max_new_tokens=20))
        engine.submit(Request("s0", (4, 5), max_new_tokens=3))
        done = []
        for step in range(200):
            if step == 4:  # joins while "long" is mid-decode
                engine.submit(Request("s1", (6, 7), max_new_tokens=3))
            done.extend(engine.step())
            if len(done) == 3 and not engine.active_count:
                break
        by_id = {r.request_id: r for r in done}
        assert set(by_id) == {"long", "s0", "s1"}
        assert all(r.outcome == "completed" for r in done)
        # the short late joiner finished before the long early one:
        # continuous batching's observable win
        order = [r.request_id for r in done]
        assert order.index("s1") < order.index("long")
        assert engine.kv.ledger.blocks_in_use == 0

    def test_drain_policy_completes_in_waves(self, reg):
        cfg, params = _tiny()
        engine = _engine(cfg, params, num_slots=2, policy="drain")
        for i in range(4):
            engine.submit(Request(f"r{i}", (1, 2), max_new_tokens=4))
        results = engine.run_to_completion()
        assert len(results) == 4
        assert all(r.outcome == "completed" for r in results)
        assert engine.kv.ledger.blocks_in_use == 0

    def test_too_long_request_fails_at_admission(self, reg):
        cfg, params = _tiny()
        engine = _engine(cfg, params, max_len=16)
        engine.submit(Request("huge", tuple(range(1, 13)),
                              max_new_tokens=8))  # 12 + 7 > 16
        results = engine.run_to_completion()
        assert [(r.outcome, r.reason) for r in results] == \
            [("failed", "too_long")]
        assert engine.kv.ledger.blocks_in_use == 0

    def test_cache_pressure_requeues_until_blocks_free(self, reg):
        cfg, params = _tiny()
        # budget fits one 2-block request at a time
        engine = _engine(cfg, params, num_slots=2, max_len=16,
                         total_blocks=2)
        engine.submit(Request("a", tuple(range(1, 9)), max_new_tokens=4))
        engine.submit(Request("b", tuple(range(1, 9)), max_new_tokens=4))
        results = engine.run_to_completion()
        assert sorted(r.request_id for r in results) == ["a", "b"]
        assert all(r.outcome == "completed" for r in results)
        assert engine.kv.ledger.blocks_in_use == 0

    def test_deadline_mid_decode_fails_loudly(self, reg):
        cfg, params = _tiny()
        clock = FakeClock()
        queue = AdmissionQueue(max_depth=8, admission_timeout_s=1e9,
                               clock=clock)
        engine = _engine(cfg, params, queue=queue, clock=clock)
        engine.submit(Request("slow", (1, 2), max_new_tokens=20,
                              deadline_s=5.0))
        engine.step()  # prefill + first decode, t=0
        clock.t = 6.0  # blow the deadline mid-stream
        results = []
        for _ in range(5):
            results.extend(engine.step())
            if results:
                break
        assert [(r.outcome, r.reason) for r in results] == \
            [("failed", "deadline")]
        assert engine.kv.ledger.blocks_in_use == 0

    def test_slo_metrics_emitted(self, reg):
        cfg, params = _tiny()
        engine = _engine(cfg, params)
        engine.submit(Request("a", (3, 1, 4), max_new_tokens=5))
        engine.run_to_completion()
        snap = reg.snapshot()
        for want in ("hvd_serve_requests_total",
                     "hvd_serve_tokens_total",
                     "hvd_serve_ttft_seconds",
                     "hvd_serve_intertoken_seconds",
                     "hvd_serve_active_slots",
                     "hvd_serve_kv_blocks_in_use",
                     "hvd_serve_queue_depth"):
            assert want in snap["metrics"], want
        assert _value(snap, "hvd_serve_requests_total",
                      outcome="completed") == 1.0
        assert _value(snap, "hvd_serve_tokens_total",
                      phase="decode") == 5.0
        # histograms carry observations: TTFT once, intertoken 4x
        assert _value(snap, "hvd_serve_ttft_seconds") == 1
        assert _value(snap, "hvd_serve_intertoken_seconds") == 4
        kinds = {e["event"] for e in snap["events"]}
        assert {"serve_admit", "serve_retire"} <= kinds
