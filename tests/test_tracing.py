"""Fast unit tests for the tracing plane (utils/tracing.py) and the
postmortem analyzer (tools/hvd_postmortem.py): the span model, the
flight-recorder rings and dump format, and the cross-rank merge math —
everything that must hold BEFORE the multi-rank chaos drill in
tests/test_chaos_plane.py exercises the same machinery end to end.
No coordinator, no processes: these run in the CI quick gate."""

import json
import os
import signal
import sys

import pytest

from horovod_tpu.utils import metrics as hvd_metrics
from horovod_tpu.utils import tracing as hvd_tracing

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import hvd_postmortem  # noqa: E402


@pytest.fixture
def tracer():
    """A live tracer at rank 3 over a live metrics registry, torn down
    to the env-driven defaults afterwards."""
    hvd_metrics.reset(enabled=True)
    t = hvd_tracing.reset(enabled=True, rank=3)
    yield t
    hvd_tracing.reset()
    hvd_metrics.reset()


class TestSpanModel:
    def test_trace_ids_mint_and_lookup(self, tracer):
        a = tracer.new_trace_id("grad_0")
        b = tracer.new_trace_id("grad_1")
        assert a == "r3.1" and b == "r3.2"
        assert tracer.trace_id_for("grad_0") == a
        assert tracer.trace_id_for("never_seen") is None
        # a fresh id for the same tensor supersedes (latest wins)
        c = tracer.new_trace_id("grad_0")
        assert tracer.trace_id_for("grad_0") == c

    def test_span_reuses_tensor_trace_id(self, tracer):
        tid = tracer.new_trace_id("g")
        s = tracer.span(hvd_tracing.NEGOTIATE, tensor="g")
        assert s.trace_id == tid
        other = tracer.span(hvd_tracing.ENQUEUE, tensor="h")
        assert other.trace_id != tid  # unseen tensor mints its own
        s.close()
        other.close()

    def test_close_is_idempotent_and_moves_to_ring(self, tracer):
        s = tracer.span(hvd_tracing.EXECUTE, tensor="t", op="allreduce")
        assert s.open and s in tracer.open_spans()
        s.close(bytes=128)
        assert not s.open and s.status == "ok"
        assert tracer.open_spans() == []
        s.close(status="error")  # second close: no-op
        assert s.status == "ok"
        (rec,) = tracer.spans()
        assert rec["stage"] == hvd_tracing.EXECUTE
        assert rec["attrs"]["bytes"] == 128 and rec["attrs"]["op"] == \
            "allreduce"
        assert rec["end_us"] >= rec["start_us"]

    def test_abort_records_error(self, tracer):
        s = tracer.span(hvd_tracing.NEGOTIATE, tensor="t")
        s.abort(ValueError("ranks [2] are lost"))
        assert s.status == "error"
        (rec,) = tracer.spans()
        assert "are lost" in rec["attrs"]["error"]

    def test_context_manager_aborts_on_exception(self, tracer):
        with pytest.raises(RuntimeError):
            with tracer.span(hvd_tracing.FUSION) as s:
                raise RuntimeError("boom")
        assert s.status == "error"
        assert "RuntimeError: boom" in s.attrs["error"]
        with tracer.span(hvd_tracing.CALLBACK) as s2:
            s2.annotate(n=1)
        assert s2.status == "ok"

    def test_parent_links(self, tracer):
        ex = tracer.span(hvd_tracing.EXECUTE, tensor="t")
        cb = tracer.span(hvd_tracing.CALLBACK, tensor="t", parent=ex)
        assert cb.parent_id == ex.span_id
        cb.close()
        ex.close()
        by_stage = {r["stage"]: r for r in tracer.spans()}
        assert by_stage["callback"]["parent_id"] == \
            by_stage["execute"]["span_id"]


class TestFlightRecorder:
    def test_span_ring_bounds_and_drop_count(self):
        t = hvd_tracing.Tracer(rank=0, span_ring=4, cycle_ring=2)
        for i in range(6):
            t.span(hvd_tracing.ENQUEUE, tensor=f"t{i}").close()
        assert len(t.spans()) == 4
        assert [r["tensor"] for r in t.spans()] == \
            ["t2", "t3", "t4", "t5"]  # oldest evicted
        assert t.flight_snapshot()["spans_dropped"] == 2
        for i in range(3):
            t.record_cycle(req_id=i)
        assert [c["req_id"] for c in t.cycles()] == [1, 2]

    def test_flight_snapshot_schema(self, tracer):
        open_span = tracer.span(hvd_tracing.NEGOTIATE, tensor="stuck")
        tracer.span(hvd_tracing.ENQUEUE, tensor="done").close()
        tracer.record_cycle(req_id=7, ack=6)
        snap = tracer.flight_snapshot("unit_test")
        assert snap["version"] == hvd_tracing.FLIGHT_VERSION
        assert snap["rank"] == 3 and snap["reason"] == "unit_test"
        assert snap["epoch_us_at_ts0"] > 0 and snap["ts_us"] >= 0
        assert [s["tensor"] for s in snap["open_spans"]] == ["stuck"]
        assert [s["tensor"] for s in snap["spans"]] == ["done"]
        assert snap["cycles"][0]["req_id"] == 7
        assert isinstance(snap["events"], list)
        json.dumps(snap)  # the whole thing must be JSON-serializable
        open_span.close()

    def test_dump_writes_file_and_counts(self, tracer, tmp_path):
        tracer._dump_dir = str(tmp_path)
        tracer.span(hvd_tracing.ENQUEUE, tensor="t").close()
        path = tracer.dump("drill")
        assert path == str(tmp_path / "flight-rank3.json")
        with open(path) as f:
            snap = json.load(f)
        assert snap["rank"] == 3 and snap["reason"] == "drill"
        reg = hvd_metrics.get_registry()
        assert reg.counter(
            "hvd_flight_dumps_total",
            labels=("reason",)).labels(reason="drill").value == 1

    def test_dump_never_raises(self, tracer, tmp_path):
        blocker = tmp_path / "file"
        blocker.write_text("")
        # dirname is a regular file: makedirs/open must fail — quietly
        assert tracer.dump("x", path=str(blocker / "sub" / "d.json")) \
            is None

    def test_slow_span_event_and_histogram(self):
        hvd_metrics.reset(enabled=True)
        try:
            t = hvd_tracing.Tracer(rank=1, slow_ms=0.0)  # everything slow
            t.span(hvd_tracing.EXECUTE, tensor="big",
                   trace_id="r1.9").close()
            reg = hvd_metrics.get_registry()
            (ev,) = [e for e in reg.events() if e["event"] == "slow_span"]
            assert ev["tensor"] == "big" and ev["trace_id"] == "r1.9"
            assert ev["stage"] == hvd_tracing.EXECUTE
            assert "hvd_span_seconds" in reg.snapshot()["metrics"]
        finally:
            hvd_metrics.reset()

    def test_write_remote_dump(self, tracer, tmp_path):
        tracer._dump_dir = str(tmp_path)
        payload = {"rank": 5, "spans": [], "reason": "coordinator_request"}
        path = hvd_tracing.write_remote_dump(payload)
        assert path == str(tmp_path / "flight-rank5.json")
        assert json.load(open(path))["rank"] == 5
        assert hvd_tracing.write_remote_dump("not a dict") is None


class TestLifecycleAndGates:
    def test_null_tracer_absorbs_everything(self):
        t = hvd_tracing.reset(enabled=False)
        try:
            assert not t.enabled
            assert t.new_trace_id("x") is None
            assert t.trace_id_for("x") is None
            s = t.span(hvd_tracing.ENQUEUE, tensor="x")
            assert s is hvd_tracing._NULL_SPAN
            assert s.annotate(a=1).close().abort() is s
            with pytest.raises(ValueError):
                with t.span(hvd_tracing.STEP):  # must not swallow
                    raise ValueError("boom")
            assert t.spans() == [] and t.cycles() == []
            assert t.dump("x") is None
            assert t.flight_snapshot()["disabled"] is True
        finally:
            hvd_tracing.reset()

    def test_env_gate_and_set_rank(self, monkeypatch):
        monkeypatch.setenv("HVD_TRACE", "0")
        hvd_tracing.reset()
        assert not hvd_tracing.get_tracer().enabled
        monkeypatch.setenv("HVD_TRACE", "1")
        hvd_tracing.reset()
        t = hvd_tracing.get_tracer()
        assert t.enabled and t.rank is None
        hvd_tracing.set_rank(4)
        assert t.rank == 4
        assert t.new_trace_id().startswith("r4.")
        hvd_tracing.reset()

    def test_sigterm_dump_chains_previous_handler(
            self, tracer, tmp_path, monkeypatch):
        tracer._dump_dir = str(tmp_path)
        tracer.span(hvd_tracing.STEP).close()
        hits = []
        orig = signal.getsignal(signal.SIGTERM)
        monkeypatch.setattr(hvd_tracing, "_sigterm_installed", False)
        monkeypatch.setattr(hvd_tracing, "_sigterm_prev", None)
        try:
            signal.signal(signal.SIGTERM, lambda *a: hits.append(a))
            assert hvd_tracing.install_signal_dump()
            os.kill(os.getpid(), signal.SIGTERM)
            assert hits, "previous handler must still run"
            assert (tmp_path / "flight-rank3.json").exists()
            snap = json.load(open(tmp_path / "flight-rank3.json"))
            assert snap["reason"] == "sigterm"
        finally:
            signal.signal(signal.SIGTERM, orig)

    def test_sigterm_install_respects_env_gate(self, monkeypatch):
        monkeypatch.setenv("HVD_FLIGHT_SIGTERM", "0")
        monkeypatch.setattr(hvd_tracing, "_sigterm_installed", False)
        assert hvd_tracing.install_signal_dump() is False

    def test_sigterm_dump_defers_to_later_wrapping_handler(self, tmp_path):
        """The dump handler re-delivers SIGTERM (SIG_DFL) only while it is
        the OUTERMOST disposition. When a later-installed handler wraps it
        — the Checkpointer's preemption flag chains to it for the dump —
        re-delivering would kill the process mid-step and break the
        finish-step -> emergency-save -> exit-45 contract. Subprocess:
        a regression here terminates the victim, not the test run."""
        import subprocess
        script = (
            "import os, signal, sys\n"
            "from horovod_tpu.utils import tracing\n"
            "tracing.reset(enabled=True, rank=0)\n"
            "assert tracing.install_signal_dump()\n"
            "flag = []\n"
            "prev = signal.getsignal(signal.SIGTERM)\n"
            "def outer(signum, frame):\n"
            "    flag.append(signum)\n"
            "    prev(signum, frame)\n"
            "signal.signal(signal.SIGTERM, outer)\n"
            "os.kill(os.getpid(), signal.SIGTERM)\n"
            "assert flag, 'outer handler must have run'\n"
            "print('SURVIVED')\n")
        env = dict(os.environ, HVD_FLIGHT_DIR=str(tmp_path))
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "SURVIVED" in proc.stdout
        # and alone — no wrapper — it still re-delivers: exit by SIGTERM
        solo = (
            "import os, signal\n"
            "from horovod_tpu.utils import tracing\n"
            "tracing.reset(enabled=True, rank=0)\n"
            "assert tracing.install_signal_dump()\n"
            "os.kill(os.getpid(), signal.SIGTERM)\n")
        proc = subprocess.run([sys.executable, "-c", solo], env=env,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == -signal.SIGTERM


# -- postmortem merge math --------------------------------------------------

def _dump(rank, anchor, spans=(), open_spans=(), cycles=(), events=(),
          reason="test"):
    return {"version": 1, "rank": rank, "reason": reason, "ts_us": 10_000,
            "epoch_us_at_ts0": anchor, "spans": list(spans),
            "open_spans": list(open_spans), "cycles": list(cycles),
            "spans_dropped": 0, "events": list(events),
            "_path": f"flight-rank{rank}.json"}


def _neg_span(tensor, trace_id, start_us, end_us=None, cycle=None,
              **attrs):
    s = {"trace_id": trace_id, "span_id": 1, "stage": "negotiate",
         "rank": None, "tensor": tensor, "start_us": start_us,
         "end_us": end_us, "status": "ok" if end_us else "open"}
    if cycle is not None:
        attrs["cycle"] = cycle
    if attrs:
        s["attrs"] = attrs
    return s


class TestPostmortem:
    def test_rebase_anchors_ranks_onto_one_clock(self):
        # rank 1 started 1s after rank 0: same ts_us, 1s apart merged
        d0 = _dump(0, 1_000_000,
                   spans=[_neg_span("g", "r0.1", 100, 200, cycle=1)])
        d1 = _dump(1, 2_000_000,
                   spans=[_neg_span("g", "r1.1", 100, 200, cycle=1)])
        base = hvd_postmortem.rebase([d0, d1])
        assert base == 1_000_000
        assert d0["spans"][0]["t0_us"] == 100
        assert d1["spans"][0]["t0_us"] == 1_000_100
        assert d1["spans"][0]["t1_us"] == 1_000_200

    def test_rebase_prefers_event_epoch_stamp(self):
        d = _dump(0, 1_000_000,
                  events=[{"event": "stall", "epoch_us": 1_500_000},
                          {"event": "stall", "ts_us": 300}])
        hvd_postmortem.rebase([d])
        assert d["events"][0]["t_us"] == 500_000
        assert d["events"][1]["t_us"] == 300

    def test_stitch_groups_by_cycle_and_tensor(self):
        d0 = _dump(0, 0, spans=[_neg_span("g", "r0.1", 0, 10, cycle=4),
                                _neg_span("h", "r0.2", 0, 10)])  # no cycle
        d1 = _dump(1, 0, spans=[_neg_span("g", "r1.1", 5, 15, cycle=4)])
        groups = hvd_postmortem.stitch([d0, d1])
        assert set(groups) == {(4, "g")}
        assert sorted(groups[(4, "g")]) == [0, 1]

    def test_analyze_names_divergent_rank_and_tensor(self):
        # ranks 0 and 1 wait on grad_7; rank 2 never enqueued it and the
        # coordinator declared rank 2 lost — verdict must say both
        waiting = _neg_span("grad_7", "r0.3", 50)
        d0 = _dump(0, 0, open_spans=[waiting],
                   events=[{"event": "ranks_lost", "ranks": [2]}])
        d1 = _dump(1, 0, open_spans=[_neg_span("grad_7", "r1.3", 60)])
        d2 = _dump(2, 0, cycles=[{"kind": "chaos_injection",
                                  "fault": "drop_response", "ts_us": 1}])
        hvd_postmortem.rebase([d0, d1, d2])
        v = hvd_postmortem.analyze([d0, d1, d2])
        assert v["divergent_rank"] == 2
        assert v["tensor"] == "grad_7" and v["trace_id"] == "r0.3"
        assert v["never_enqueued"] == {"grad_7": [2]}
        assert v["waiting"] == {"grad_7": [0, 1]}
        assert len(v["chaos_injections"]) == 1
        assert any("never enqueued" in r for r in v["reasons"])

    def test_main_json_and_trace(self, tmp_path, capsys):
        for d in (_dump(0, 0,
                        spans=[_neg_span("g", "r0.1", 0, 10, cycle=2)],
                        open_spans=[_neg_span("stuck", "r0.2", 5)]),
                  _dump(1, 0,
                        spans=[_neg_span("g", "r1.1", 2, 12, cycle=2)])):
            p = tmp_path / f"flight-rank{d['rank']}.json"
            d.pop("_path")
            p.write_text(json.dumps(d))
        trace_out = tmp_path / "out.trace.json"
        rc = hvd_postmortem.main(["--dir", str(tmp_path), "--json",
                                  "--trace", str(trace_out)])
        assert rc == 0
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["stitched_collectives"] == 1
        assert verdict["tensor"] == "stuck"
        trace = json.loads(trace_out.read_text())
        phases = {e["ph"] for e in trace["traceEvents"]}
        assert {"X", "i", "s", "f", "M"} <= phases

    def test_main_handles_no_and_bad_dumps(self, tmp_path):
        assert hvd_postmortem.main(["--dir", str(tmp_path)]) == 2
        (tmp_path / "flight-rank0.json").write_text("{trunc")
        assert hvd_postmortem.main(["--dir", str(tmp_path)]) == 2

    def test_load_dumps_tolerates_malformed(self, tmp_path):
        good = tmp_path / "flight-rank1.json"
        good.write_text(json.dumps(
            {k: v for k, v in _dump(1, 0).items() if k != "_path"}))
        bad = tmp_path / "flight-rank0.json"
        bad.write_text("{not json")
        dumps, badlist = hvd_postmortem.load_dumps(
            [str(bad), str(good)])
        assert [d["rank"] for d in dumps] == [1]
        assert len(badlist) == 1 and str(bad) in badlist[0][0]
