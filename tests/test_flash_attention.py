"""Flash-attention kernel tests (interpret mode on CPU): numerical parity
with the reference full attention, gradients, causality, and the
transformer attention_impl='flash' wiring."""

import numpy as np
import pytest


def _qkv(rng, b=2, s=128, h=4, d=32, dtype=None):
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    r = np.random.RandomState(rng)
    mk = lambda: jnp.asarray(r.randn(b, s, h, d) * 0.3, dtype)
    return mk(), mk(), mk()


class TestFlashForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, hvd, causal):
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(0)
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        ref = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_bhsd_layout_matches_bshd(self, hvd):
        """layout="bhsd" (head-major operands, reshape-only flatten) is
        numerically identical to the default layout, forward and
        backward, including the indivisible-seq padding path."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import flash_attention
        rng = np.random.RandomState(3)
        q, k, v = (jnp.asarray(rng.randn(2, 45, 3, 16), jnp.float32)
                   for _ in range(3))

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        def bshd(q, k, v):
            return flash_attention(q, k, v, causal=True, block_q=32,
                                   block_k=32)

        def bhsd(q, k, v):
            return flash_attention(
                q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2),
                causal=True, block_q=32, block_k=32,
                layout="bhsd").swapaxes(1, 2)

        np.testing.assert_allclose(np.asarray(bshd(q, k, v)),
                                   np.asarray(bhsd(q, k, v)), atol=1e-5)
        g1 = jax.grad(loss(bshd), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss(bhsd), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_single_block(self, hvd):
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(1, s=64)
        out = flash_attention(q, k, v, block_q=64, block_k=64)
        ref = full_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_bf16_io(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(2, dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        assert out.dtype == jnp.bfloat16
        ref = full_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=5e-2, atol=5e-2)

    def test_causality(self, hvd):
        # output at position t must not depend on k/v after t
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(3, s=64)
        out1 = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        k2 = k.at[:, 40:].set(999.0)
        v2 = v.at[:, 40:].set(-999.0)
        out2 = flash_attention(q, k2, v2, causal=True, block_q=16,
                               block_k=16)
        np.testing.assert_allclose(np.asarray(out1[:, :40]),
                                   np.asarray(out2[:, :40]), rtol=1e-5)

    def test_pads_indivisible_causal(self, hvd):
        # causal self-attention end-pads to the block multiple and slices
        # back; must match the unpadded reference exactly
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(4, s=100)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        want = full_attention(q, k, v, causal=True)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_noncausal(self, hvd):
        from horovod_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(4, s=100)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, causal=False, block_q=64, block_k=64)

    def test_block_shrinks_to_fit_seq(self, hvd):
        # the 256 default must not reject lengths a 128-block handles:
        # non-causal seq 384 and cross-length causal (sq != sk) shrink the
        # block instead of raising
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(5, s=384)
        out = flash_attention(q, k, v, causal=False)
        want = full_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        q2, _, _ = _qkv(6, s=128)
        _, k2, v2 = _qkv(7, s=384)
        out2 = flash_attention(q2, k2, v2, causal=False)
        want2 = full_attention(q2, k2, v2, causal=False)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(want2),
                                   rtol=2e-5, atol=2e-5)


class TestFlashBackward:
    def test_grad_matches_reference(self, hvd):
        import jax
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(5, s=64)

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           block_q=32, block_k=32) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("bq,bk", [(32, 64), (64, 32)])
    def test_grad_asymmetric_blocks(self, hvd, bq, bk):
        """Unequal block_q/block_k exercises the diagonal start/stop index
        math (qb_start, nk) off its degenerate equal-block form."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(11, s=128)

        g_flash = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=bk) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(full_attention(
            q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_grad_non_causal(self, hvd):
        import jax
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(3, s=64)

        g_flash = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=False, block_q=32, block_k=32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(full_attention(
            q, k, v, causal=False) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_grad_padded_causal(self, hvd):
        """Backward through the end-padding path (seq 100, block 64):
        padded rows/keys must contribute exactly nothing."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel.ring import full_attention
        q, k, v = _qkv(7, s=100)

        g_flash = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=64, block_k=64) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(full_attention(
            q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestTransformerFlash:
    def test_flash_model_matches_full(self, hvd):
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        cfg_full = tr.TransformerConfig.tiny(dtype=jnp.float32)
        cfg_flash = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                              attention_impl="flash")
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg_full.vocab_size,
                                             (2, 64)), jnp.int32)
        m_full, m_flash = tr.TransformerLM(cfg_full), \
            tr.TransformerLM(cfg_flash)
        params = m_full.init(jax.random.PRNGKey(0), tokens)["params"]
        out_full = m_full.apply({"params": params}, tokens)
        out_flash = m_flash.apply({"params": params}, tokens)
        np.testing.assert_allclose(np.asarray(out_flash),
                                   np.asarray(out_full), rtol=2e-4,
                                   atol=2e-4)

    def test_flash_model_trains(self, hvd):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu.models import transformer as tr
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                        attention_impl="flash")
        model = tr.TransformerLM(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 65)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
        loss_fn = tr.lm_loss_fn(model)
        tx = optax.adamw(3e-3)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]


class TestDecodeAttention:
    """Single-query decode path (serving plane): numerics against the
    reference full attention and KV-cached generation parity."""

    @pytest.mark.parametrize("length", [1, 5, 24, 64])
    def test_matches_full_attention_last_row(self, hvd, length):
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import decode_attention
        from horovod_tpu.parallel.ring import full_attention
        s_max = 64
        q_all, k, v = _qkv(0, b=2, s=s_max, h=4, d=32)
        # causal full attention over the first `length` tokens: its last
        # row is exactly one query attending a `length`-long prefix
        ref = full_attention(q_all[:, :length], k[:, :length],
                             v[:, :length], causal=True)[:, -1:]
        lengths = jnp.full((2,), length, jnp.int32)
        out = decode_attention(q_all[:, length - 1:length], k, v, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_masks_beyond_length_per_row(self, hvd):
        """Garbage K/V past each row's length must not leak into the
        output — rows with different lengths, same padded cache."""
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import decode_attention
        q, k, v = _qkv(1, b=2, s=32, h=2, d=16)
        lengths = jnp.asarray([3, 17], jnp.int32)
        out = decode_attention(q[:, :1], k, v, lengths)
        # poison the tail beyond each row's length: output unchanged
        k2 = k.at[0, 3:].set(1e4).at[1, 17:].set(-1e4)
        v2 = v.at[0, 3:].set(1e4).at[1, 17:].set(-1e4)
        out2 = decode_attention(q[:, :1], k2, v2, lengths)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_preserves_query_dtype(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import decode_attention
        q, k, v = _qkv(2, b=1, s=16, h=2, d=16, dtype=jnp.bfloat16)
        out = decode_attention(q[:, :1], k, v,
                               jnp.asarray([9], jnp.int32))
        assert out.dtype == jnp.bfloat16
        assert out.shape == (1, 1, 2, 16)

    def test_rejects_multi_query(self, hvd):
        import jax.numpy as jnp
        from horovod_tpu.ops.flash_attention import decode_attention
        q, k, v = _qkv(3, b=1, s=8, h=2, d=16)
        with pytest.raises(ValueError):
            decode_attention(q, k, v, jnp.asarray([8], jnp.int32))


class TestKVCachedGeneration:
    def test_cached_greedy_matches_no_cache_token_for_token(self, hvd):
        """Prefill + decode_attention steps reproduce the no-cache
        full-forward greedy continuation exactly (temp 0, fp32)."""
        import jax
        import jax.numpy as jnp
        from horovod_tpu.models import transformer as tr
        from horovod_tpu.serving.decode import (decode_step,
                                                prefill_forward)
        cfg = tr.TransformerConfig.tiny(dtype=jnp.float32,
                                        attention_impl="full")
        model, params = tr.init_params(cfg, jax.random.PRNGKey(0))
        prompt = [7, 3, 11, 19, 2]
        n_new = 12

        # reference: full forward over the growing sequence every step
        ref_toks = list(prompt)
        ref_out = []
        for _ in range(n_new):
            logits = model.apply({"params": params},
                                 jnp.asarray([ref_toks], jnp.int32))
            nxt = int(jnp.argmax(logits[0, -1]))
            ref_out.append(nxt)
            ref_toks.append(nxt)

        # cached: one prefill, then single-token decode steps
        max_len = 32
        logits, pk, pv = prefill_forward(
            cfg, params, jnp.asarray([prompt], jnp.int32))
        kv_k = jnp.zeros((cfg.num_layers, 1, max_len, cfg.num_heads,
                          cfg.d_model // cfg.num_heads), cfg.dtype)
        kv_v = jnp.zeros_like(kv_k)
        kv_k = kv_k.at[:, :, :len(prompt)].set(pk)
        kv_v = kv_v.at[:, :, :len(prompt)].set(pv)
        tok = int(jnp.argmax(logits[0, -1]))
        got = [tok]
        pos = len(prompt)
        for _ in range(n_new - 1):
            logits, kv_k, kv_v = decode_step(
                cfg, params, jnp.asarray([tok], jnp.int32),
                jnp.asarray([pos], jnp.int32), kv_k, kv_v)
            tok = int(jnp.argmax(logits[0]))
            got.append(tok)
            pos += 1
        assert got == ref_out
