"""Router plane (horovod_tpu/router/): dispatch scoring math, cache-
affinity stickiness, the exactly-once reroute ledger on replica loss,
and the SLO-gated canary state machine on synthetic histograms. All
process-local — the router sees engines through a four-method surface
(submit/step/load_snapshot/active_count + queue), so a test double
stands in and no jax is imported. The 2-process replica-loss and
poisoned-canary drills ride test_chaos_plane.py."""

import pytest

from horovod_tpu.router import CanaryController, Router
from horovod_tpu.router import canary as route_canary
from horovod_tpu.router import policy as route_policy
from horovod_tpu.serving.queue import Request, RequestResult
from horovod_tpu.utils import metrics as hvd_metrics


@pytest.fixture
def reg():
    r = hvd_metrics.reset(enabled=True)
    yield r
    hvd_metrics.reset()


def _value(snap, name, **labels):
    fam = snap["metrics"].get(name)
    if fam is None:
        return None
    for v in fam["values"]:
        if all(v["labels"].get(k) == lv for k, lv in labels.items()):
            return v.get("value", v.get("count"))
    return None


def _events(snap, kind):
    return [e for e in snap["events"] if e["event"] == kind]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeEngine:
    """ServeEngine stand-in: holds admitted requests until the test
    says finish(), and lets the test pin the load snapshot exactly."""

    def __init__(self, accept=True, generation=1):
        self.accept = accept
        self.generation = generation
        self.queue = []   # router pending() len()s this
        self.held = {}    # request_id -> Request
        self.load = None  # pinned snapshot; None = derive from held
        self._done = []

    def submit(self, request):
        if not self.accept:
            return False
        self.held[request.request_id] = request
        return True

    @property
    def active_count(self):
        return len(self.held)

    def load_snapshot(self):
        if self.load is not None:
            return dict(self.load)
        return {"queue_depth": 0, "active_slots": len(self.held),
                "work_tokens": sum(r.max_new_tokens
                                   for r in self.held.values()),
                "free_slots": 8 - len(self.held), "free_blocks": 8,
                "generation": self.generation,
                "armed_generation": None}

    def finish(self, request_id, tokens=(5, 6, 7)):
        req = self.held.pop(request_id)
        self._done.append(RequestResult(
            req.request_id, tuple(tokens), "completed", ttft_s=0.01,
            generation=self.generation))

    def step(self):
        out, self._done = self._done, []
        return out


def _req(i, prompt=None, max_new_tokens=8):
    return Request(request_id=f"r{i}",
                   prompt=prompt if prompt is not None
                   else (100 + i, 200 + i, 300 + i),
                   max_new_tokens=max_new_tokens)


# ---------------------------------------------------------------------------
# policy scoring math
# ---------------------------------------------------------------------------

class TestPolicyScore:
    def test_missing_snapshot_scores_idle(self):
        assert route_policy.score(None) == 0.0
        assert route_policy.score({}) == 0.0

    def test_weighted_sum(self):
        load = {"queue_depth": 2, "active_slots": 3, "work_tokens": 8,
                "free_blocks": 4}
        assert route_policy.score(load) == pytest.approx(
            2 * route_policy.QUEUE_WEIGHT + 3 * route_policy.SLOT_WEIGHT
            + 8 * route_policy.WORK_WEIGHT)

    def test_kv_exhaustion_penalty_dominates_queue_depth(self):
        exhausted = route_policy.score({"queue_depth": 0,
                                        "free_blocks": 0})
        assert exhausted == route_policy.KV_EXHAUSTED_PENALTY
        # a deep queue with blocks free still beats an exhausted replica
        assert route_policy.score({"queue_depth": 10,
                                   "free_blocks": 5}) < exhausted

    def test_work_term_separates_equal_queue_depths(self):
        # a queued 40-token request predicts more occupancy than a
        # queued 8-token one even though queue_depth says they're equal
        long = route_policy.score({"queue_depth": 1, "work_tokens": 40})
        short = route_policy.score({"queue_depth": 1, "work_tokens": 8})
        assert long > short

    def test_round_robin_cycles_id_order(self):
        p = route_policy.RoundRobin()
        picks = [p.choose([2, 0, 1], {}) for _ in range(4)]
        assert picks == [0, 1, 2, 0]

    def test_least_loaded_picks_min_with_id_tiebreak(self):
        p = route_policy.LeastLoaded()
        loads = {0: {"queue_depth": 2}, 1: {"queue_depth": 1},
                 2: {"queue_depth": 1}}
        assert p.choose([0, 1, 2], loads) == 1  # min score, lowest id
        assert p.choose([0, 2], loads) == 2

    def test_least_loaded_treats_unreported_as_idle(self):
        p = route_policy.LeastLoaded()
        # replica 3 has never heartbeated: routable, assumed idle
        assert p.choose([0, 3], {0: {"queue_depth": 1}}) == 3

    def test_prefix_key(self):
        assert route_policy.prefix_key((1, 2, 3, 4), 2) == (1, 2)
        assert route_policy.prefix_key((1, 2), 8) == (1, 2)
        assert route_policy.prefix_key((1, 2), 0) is None
        assert route_policy.prefix_key((), 8) is None

    def test_resolve_env_and_unknown(self, monkeypatch):
        assert isinstance(route_policy.resolve("round_robin"),
                          route_policy.RoundRobin)
        monkeypatch.setenv("HVD_ROUTE_POLICY", "round_robin")
        assert isinstance(route_policy.resolve(),
                          route_policy.RoundRobin)
        monkeypatch.delenv("HVD_ROUTE_POLICY")
        assert isinstance(route_policy.resolve(),
                          route_policy.LeastLoaded)
        with pytest.raises(ValueError, match="HVD_ROUTE_POLICY"):
            route_policy.resolve("fastest_ever")


# ---------------------------------------------------------------------------
# dispatch + affinity stickiness
# ---------------------------------------------------------------------------

class TestDispatch:
    def test_least_loaded_alternates_idle_replicas(self, reg):
        engines = {0: FakeEngine(), 1: FakeEngine()}
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=0)
        for i in range(4):
            assert router.submit(_req(i))
        assert sorted(engines[0].held) == ["r0", "r2"]
        assert sorted(engines[1].held) == ["r1", "r3"]
        snap = reg.snapshot()
        assert _value(snap, "hvd_route_requests_total", replica="0") == 2
        assert _value(snap, "hvd_route_requests_total", replica="1") == 2
        assert router.inflight == {"r0": 0, "r1": 1, "r2": 0, "r3": 1}

    def test_step_stamps_serving_replica(self, reg):
        engines = {0: FakeEngine(), 1: FakeEngine()}
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=0)
        router.submit(_req(0))
        router.submit(_req(1))
        engines[1].finish("r1")
        (res,) = router.step()
        assert (res.request_id, res.replica, res.rerouted) == (
            "r1", 1, False)
        assert router.inflight == {"r0": 0}
        assert router.pending()
        engines[0].finish("r0")
        router.step()
        assert not router.pending()

    def test_affinity_sticks_within_slack_then_overflows(self, reg):
        engines = {0: FakeEngine(), 1: FakeEngine()}
        engines[0].load = {"queue_depth": 0}
        engines[1].load = {"queue_depth": 0}
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=4)
        prefix = (1, 2, 3, 4)
        # first sighting: miss, pins the prefix to the policy pick (0)
        router.submit(Request("a0", prefix + (9,)))
        assert "a0" in engines[0].held
        # sticky replica costs AFFINITY_SLACK more than the pick: the
        # warmth still wins (score gap 8 <= slack 8)
        engines[0].load = {"queue_depth": 2}
        router.submit(Request("a1", prefix + (8,)))
        assert "a1" in engines[0].held
        # past the slack, load wins and the prefix re-pins to 1
        engines[0].load = {"queue_depth": 3}
        router.submit(Request("a2", prefix + (7,)))
        assert "a2" in engines[1].held
        router.submit(Request("a3", prefix + (6,)))
        assert "a3" in engines[1].held  # re-pinned: hit on 1
        snap = reg.snapshot()
        assert _value(snap, "hvd_route_affinity_total",
                      outcome="miss") == 1
        assert _value(snap, "hvd_route_affinity_total",
                      outcome="hit") == 2
        assert _value(snap, "hvd_route_affinity_total",
                      outcome="overflow") == 1

    def test_distinct_prefixes_do_not_share_stickiness(self, reg):
        engines = {0: FakeEngine(), 1: FakeEngine()}
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=4)
        router.submit(Request("p0", (1, 1, 1, 1, 5)))
        router.submit(Request("p1", (2, 2, 2, 2, 5)))
        assert "p0" in engines[0].held
        assert "p1" in engines[1].held  # its own miss, not p0's pin

    def test_rejecting_replica_surfaces_backpressure(self, reg):
        router = Router({0: FakeEngine(accept=False)},
                        affinity_prefix=0)
        assert not router.submit(_req(0))
        assert router.inflight == {}


# ---------------------------------------------------------------------------
# replica loss -> exactly-once reroute
# ---------------------------------------------------------------------------

class TestReroute:
    def _router(self, clock=None):
        engines = {0: FakeEngine(), 1: FakeEngine()}
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=0, reroute_window_s=30.0,
                        clock=clock or FakeClock())
        for i in range(4):
            router.submit(_req(i))
        return engines, router

    def test_loss_requeues_to_survivor_exactly_once(self, reg):
        engines, router = self._router()
        router.on_ranks_lost([1])
        assert router.live_replicas() == [0]
        # r1/r3 moved off the dead replica; survivors hold each exactly
        # once and the ledger points every request at replica 0
        assert sorted(engines[0].held) == ["r0", "r1", "r2", "r3"]
        assert set(router.inflight.values()) == {0}
        # a second loss notification for the same replica is idempotent
        router.on_ranks_lost([1])
        assert sorted(engines[0].held) == ["r0", "r1", "r2", "r3"]
        snap = reg.snapshot()
        assert _value(snap, "hvd_route_rerouted_total") == 2
        assert _value(snap, "hvd_route_replicas_live") == 1
        lost = _events(snap, "route_replica_lost")
        assert [e["inflight"] for e in lost] == [["r1", "r3"], []]
        moves = _events(snap, "route_reroute")
        assert {(e["request_id"], e["from_replica"], e["to_replica"])
                for e in moves} == {("r1", 1, 0), ("r3", 1, 0)}

    def test_rerouted_results_are_stamped(self, reg):
        engines, router = self._router()
        router.on_ranks_lost([1])
        for rid in list(engines[0].held):
            engines[0].finish(rid)
        results = {r.request_id: r for r in router.step()}
        assert len(results) == 4  # each request finishes exactly once
        assert {k for k, r in results.items() if r.rerouted} == {
            "r1", "r3"}
        assert all(r.replica == 0 for r in results.values())
        assert not router.pending()

    def test_stale_request_fails_loud_instead_of_resurrecting(self, reg):
        clock = FakeClock()
        engines, router = self._router(clock)
        clock.t = 31.0  # past the 30s reroute window
        router.on_ranks_lost([1])
        assert sorted(engines[0].held) == ["r0", "r2"]  # no resurrection
        failed = {r.request_id: r for r in router.step()
                  if r.outcome == "failed"}
        assert sorted(failed) == ["r1", "r3"]
        assert all(r.reason == "reroute_window" and r.replica == 1
                   for r in failed.values())

    def test_no_survivors_fails_the_orphans(self, reg):
        router = Router({0: FakeEngine()}, affinity_prefix=0,
                        clock=FakeClock())
        router.submit(_req(0))
        router.on_ranks_lost([0])
        (res,) = router.step()
        assert (res.outcome, res.reason) == ("failed", "no_survivors")
        assert router.inflight == {}

    def test_survivor_rejection_fails_not_drops(self, reg):
        engines = {0: FakeEngine(), 1: FakeEngine()}
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=0, clock=FakeClock())
        router.submit(_req(0))  # lands on replica 0
        engines[1].accept = False
        router.on_ranks_lost([0])
        (res,) = router.step()
        assert (res.outcome, res.reason) == ("failed",
                                             "reroute_rejected")


# ---------------------------------------------------------------------------
# canary rollout on synthetic histograms
# ---------------------------------------------------------------------------

def _canary(reg, **kw):
    kw.setdefault("pct", 50.0)
    kw.setdefault("window", 4)
    kw.setdefault("min_delta_s", 0.025)
    return CanaryController(clock=FakeClock(), **kw)


def _armed_loads(gen=2, replica=1):
    return {0: {"generation": 1, "armed_generation": None},
            replica: {"generation": 1, "armed_generation": gen}}


def _res(i, gen, ttft=0.008, tokens=8, outcome="completed",
         decode_ms=None):
    return RequestResult(
        f"c{i}", tuple(range(tokens)), outcome, ttft_s=ttft,
        generation=gen,
        phase_ms={"decode": decode_ms} if decode_ms is not None
        else None)


def _fill(ctrl, gen_baseline=1, gen_canary=2, canary_ttft=0.008,
          baseline_ttft=0.008, canary_outcomes=("completed",) * 4):
    for i in range(ctrl.window):
        ctrl.observe(_res(f"b{i}", gen_baseline, ttft=baseline_ttft), 0)
    for i, outcome in enumerate(canary_outcomes):
        ctrl.observe(_res(f"k{i}", gen_canary, ttft=canary_ttft,
                          outcome=outcome), 1)


class TestCanary:
    def test_tick_begins_on_armed_generation(self, reg):
        ctrl = _canary(reg)
        ctrl.tick({0: {"generation": 1, "armed_generation": None}})
        assert ctrl.state == "idle"
        ctrl.tick(_armed_loads(gen=2, replica=1))
        assert ctrl.state == "canary"
        assert ctrl.canary_generation == 2
        assert ctrl.canary_replicas == frozenset([1])
        (begin,) = _events(reg.snapshot(), "route_canary_begin")
        assert begin["generation"] == 2 and begin["replicas"] == [1]

    def test_cohort_bounded_when_everyone_arms(self, reg):
        ctrl = _canary(reg, max_canary_replicas=1)
        ctrl.tick({r: {"generation": 1, "armed_generation": 2}
                   for r in range(4)})
        assert ctrl.canary_replicas == frozenset([0])  # first id only
        assert not ctrl.allows_swap(3, 2)  # the rest hold as baseline
        assert ctrl.allows_swap(0, 2)

    def test_filter_splits_traffic_by_stable_hash(self, reg):
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads())
        to_canary = next(f"q{i}" for i in range(200)
                         if route_canary._hash_pct(f"q{i}") < ctrl.pct)
        to_base = next(f"q{i}" for i in range(200)
                       if route_canary._hash_pct(f"q{i}") >= ctrl.pct)
        loads = {0: {"generation": 1}, 1: {"generation": 2}}
        assert ctrl.filter(to_canary, [0, 1], loads) == [1]
        assert ctrl.filter(to_base, [0, 1], loads) == [0]
        # same id, same cohort, every time — no flapping across retries
        assert ctrl.filter(to_canary, [0, 1], loads) == [1]

    def test_filter_availability_beats_cohort_discipline(self, reg):
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads())
        to_canary = next(f"q{i}" for i in range(200)
                         if route_canary._hash_pct(f"q{i}") < ctrl.pct)
        # the canary replica is gone: its traffic still has a home
        assert ctrl.filter(to_canary, [0], {0: {"generation": 1}}) == [0]

    def test_promote_on_healthy_window(self, reg):
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads())
        assert not ctrl.allows_swap(0, 2)  # holdback during canary
        _fill(ctrl, canary_ttft=0.008, baseline_ttft=0.008)
        assert ctrl.state == "promoted"
        assert ctrl.allows_swap(0, 2)  # gates open fleet-wide
        (verdict, evidence) = ctrl.decisions[-1]
        assert verdict == "promote"
        snap = reg.snapshot()
        (ev,) = _events(snap, "route_promote")
        assert ev["canary_n"] == ev["baseline_n"] == 4
        assert ev["ttft_p99_canary"] is not None
        assert _value(snap, "hvd_route_canary_fraction") == 100

    def test_rollback_on_ttft_breach_quarantines(self, reg):
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads())
        _fill(ctrl, canary_ttft=0.4, baseline_ttft=0.008)
        assert ctrl.state == "rolled_back"
        assert 2 in ctrl.quarantined
        assert not ctrl.allows_swap(0, 2)  # quarantine outlives canary
        (verdict, evidence) = ctrl.decisions[-1]
        assert verdict == "rollback"
        assert "ttft_p99" in evidence["breaches"]
        snap = reg.snapshot()
        (ev,) = _events(snap, "route_rollback")
        assert ev["ttft_p99_canary"] > ev["ttft_p99_baseline"]
        assert _value(snap, "hvd_route_canary_fraction") == 0
        # replicas already serving the quarantined generation get no
        # traffic until a newer generation arms
        loads = {0: {"generation": 2}, 1: {"generation": 1}}
        assert ctrl.filter("any", [0, 1], loads) == [1]

    def test_min_delta_floor_absorbs_bucket_quantization(self, reg):
        # ratio 2x but the absolute gap (~2.5ms) is below min_delta_s:
        # fixed buckets can't resolve it, so the verdict is promote
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads())
        _fill(ctrl, canary_ttft=0.004, baseline_ttft=0.002)
        assert ctrl.state == "promoted"

    def test_rollback_on_goodput_drop(self, reg):
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads())
        _fill(ctrl, canary_outcomes=("completed", "completed",
                                     "failed", "failed"))
        assert ctrl.state == "rolled_back"
        (verdict, evidence) = ctrl.decisions[-1]
        assert evidence["breaches"] == ["goodput_ratio"]
        assert evidence["goodput_ratio_canary"] == pytest.approx(0.5)

    def test_cohort_is_the_generation_not_the_replica(self, reg):
        # pre-swap admissions decoded on a canary REPLICA under the old
        # generation count as baseline evidence, not canary evidence
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads())
        for i in range(ctrl.window):
            ctrl.observe(_res(f"o{i}", 1), 1)  # old gen, canary replica
        assert ctrl._stats["baseline"].n == ctrl.window
        assert ctrl._stats["canary"].n == 0
        assert ctrl.state == "canary"  # canary window still empty

    def test_quarantined_generation_never_recanaries(self, reg):
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads(gen=2))
        _fill(ctrl, canary_ttft=0.4)
        assert ctrl.state == "rolled_back"
        ctrl.tick(_armed_loads(gen=2))  # same build arms again: ignored
        assert ctrl.state == "rolled_back"
        ctrl.tick(_armed_loads(gen=3))  # the fixed build starts fresh
        assert ctrl.state == "canary"
        assert ctrl.canary_generation == 3

    def test_promoted_generation_not_reevaluated(self, reg):
        ctrl = _canary(reg)
        ctrl.tick(_armed_loads(gen=2))
        _fill(ctrl)
        assert ctrl.state == "promoted"
        ctrl.tick(_armed_loads(gen=2))  # stale arming gossip: no-op
        assert ctrl.state == "promoted"
        ctrl.tick(_armed_loads(gen=3))
        assert ctrl.state == "canary" and ctrl.canary_generation == 3


# ---------------------------------------------------------------------------
# router + canary integration (fake engines, real cohort steering)
# ---------------------------------------------------------------------------

class TestRouterWithCanary:
    def test_dispatch_respects_cohort_and_results_feed_verdict(self, reg):
        engines = {0: FakeEngine(generation=1),
                   1: FakeEngine(generation=1)}
        ctrl = _canary(reg)
        router = Router(engines, policy="least_loaded",
                        affinity_prefix=0, canary=ctrl)
        # replica 1 arms generation 2: the next router step's tick sees
        # it via load snapshots and opens the canary
        engines[1].load = {"generation": 1, "armed_generation": 2}
        router.step()
        assert ctrl.state == "canary"
        engines[1].load = None
        engines[1].generation = 2  # the cohort swaps; baseline holds
        ids = [f"q{i}" for i in range(200)]
        canary_ids = [i for i in ids
                      if route_canary._hash_pct(i) < ctrl.pct][:4]
        base_ids = [i for i in ids
                    if route_canary._hash_pct(i) >= ctrl.pct][:4]
        for rid in canary_ids + base_ids:
            assert router.submit(Request(rid, (1, 2, 3)))
        assert sorted(engines[1].held) == sorted(canary_ids)
        assert sorted(engines[0].held) == sorted(base_ids)
        for rid in canary_ids:
            engines[1].finish(rid)
        for rid in base_ids:
            engines[0].finish(rid)
        router.step()  # results flow through observe() -> verdict
        assert ctrl.state == "promoted"
        assert _events(reg.snapshot(), "route_promote")
