"""Programmatic launcher + elastic supervisor tests (reference
test_spark.py:51-110 for run(fn); submitjob.py semantics for elasticity)."""

import socket
import sys
import time

import pytest

from horovod_tpu.run.elastic import ElasticSupervisor, shrink_hosts
from horovod_tpu.run.hosts import HostSlots, parse_hosts
from horovod_tpu.run.launch import run


class TestProgrammaticRun:
    """run(fn) happy path / args / failure (test_spark.py test_happy_run
    parity). Functions are defined as closures so cloudpickle ships them by
    value, as Spark closures are shipped in the reference."""

    def test_happy_run(self):
        def fn():
            import os
            return (int(os.environ["HVD_PROCESS_ID"]),
                    int(os.environ["HVD_NUM_PROC"]))

        assert run(fn, num_proc=2) == [(0, 2), (1, 2)]

    def test_args_kwargs(self):
        def fn(a, b, scale=1):
            import os
            return (a + b) * scale + int(os.environ["HVD_PROCESS_ID"])

        assert run(fn, args=(10, 5), kwargs={"scale": 2},
                   num_proc=2) == [30, 31]

    def test_worker_exception_propagates(self):
        def fn():
            import os
            if os.environ["HVD_PROCESS_ID"] == "1":
                raise ValueError("rank 1 exploded")
            return "ok"

        with pytest.raises(RuntimeError, match="rank 1 exploded"):
            run(fn, num_proc=2)

    def test_timeout(self):
        with pytest.raises(Exception, match="[Tt]imed out"):
            run(time.sleep, args=(60,), num_proc=1, start_timeout_s=2.0)


class TestShrinkHosts:
    def test_simple_removal(self):
        hosts = parse_hosts("a:4,b:4")
        new, total = shrink_hosts(hosts, 4, 8)
        assert total == 4 and new == [HostSlots("a", 4)]

    def test_divisibility_forces_extra_removal(self):
        # 8 slots, remove 3 -> 5, but 8 % 5 != 0 -> shrink to 4 (bpa 2)
        hosts = parse_hosts("a:4,b:4")
        new, total = shrink_hosts(hosts, 3, 8)
        assert total == 4
        assert sum(h.slots for h in new) == 4

    def test_removal_from_last_host_first(self):
        hosts = parse_hosts("a:2,b:2")
        new, total = shrink_hosts(hosts, 2, 4)
        assert new == [HostSlots("a", 2)]

    def test_impossible_raises(self):
        with pytest.raises(ValueError):
            shrink_hosts(parse_hosts("a:2"), 2, 2)


class TestElasticSupervisor:
    def test_restart_on_slot_removal(self, tmp_path):
        """E2E: job logs {np},{bpa}; removing slots restarts it with the
        rescaled values (submitjob.py:163-204)."""
        log = tmp_path / "runs.log"
        script = tmp_path / "job.py"
        script.write_text(
            "import sys, time\n"
            "open(sys.argv[1], 'a').write(sys.argv[2] + '\\n')\n"
            "time.sleep(60)\n")
        sup = ElasticSupervisor(
            "localhost:4",
            [sys.executable, str(script), str(log), "np={np},bpa={bpa}"],
            ports=tuple(range(15100, 15110)))
        sup.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not log.exists():
                time.sleep(0.1)
            assert log.read_text() == "np=4,bpa=1\n"

            # surrender 2 slots over TCP, as `echo 2 | nc` would
            with socket.create_connection(("127.0.0.1", sup.port)) as s:
                s.sendall(b"2")
            deadline = time.time() + 10
            while time.time() < deadline and \
                    log.read_text().count("\n") < 2:
                time.sleep(0.1)
            assert log.read_text() == "np=4,bpa=1\nnp=2,bpa=2\n"
            assert sup.restarts == 1
        finally:
            sup.shutdown()

    def test_wait_returns_job_exit_code(self):
        sup = ElasticSupervisor(
            "localhost:2", [sys.executable, "-c", "import sys; sys.exit(3)"],
            ports=tuple(range(15110, 15120)), verbose=0)
        sup.start()
        assert sup.wait(poll_s=0.1) == 3

    def test_recv_message_reassembles_split_tcp_segments(self):
        """TCP is a byte stream: one recv() may return any prefix of the
        peer's message. A '12' sent as '1' then '2' must parse as twelve
        slots, not one (the truncation bug this helper replaced)."""
        a, b = socket.socketpair()
        try:
            out = {}

            def read():
                out["msg"] = ElasticSupervisor._recv_message(a)

            import threading
            t = threading.Thread(target=read)
            t.start()
            b.sendall(b"1")
            time.sleep(0.1)  # force the second segment into its own recv
            b.sendall(b"2\n")
            b.close()
            t.join(timeout=5)
            assert out["msg"] == b"12"
        finally:
            a.close()

    def test_recv_message_bounds_size_and_time(self):
        a, b = socket.socketpair()
        try:
            b.sendall(b"9" * 200)
            b.close()
            with pytest.raises(ValueError, match="exceeds"):
                ElasticSupervisor._recv_message(a)
        finally:
            a.close()
        # a peer that connects and never closes hits the socket timeout
        a, b = socket.socketpair()
        try:
            b.sendall(b"3")
            with pytest.raises(OSError):
                ElasticSupervisor._recv_message(a, timeout_s=0.2)
        finally:
            a.close()
            b.close()

    def test_listener_survives_malformed_message(self, tmp_path):
        """Garbage on the control port must not kill the supervisor or
        the job; a later well-formed (even split-across-segments)
        message still works."""
        log = tmp_path / "runs.log"
        script = tmp_path / "job.py"
        script.write_text(
            "import sys, time\n"
            "open(sys.argv[1], 'a').write(sys.argv[2] + '\\n')\n"
            "time.sleep(60)\n")
        sup = ElasticSupervisor(
            "localhost:4",
            [sys.executable, str(script), str(log), "np={np}"],
            ports=tuple(range(15120, 15130)), verbose=0)
        sup.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not log.exists():
                time.sleep(0.1)
            for junk in (b"not a number", b"", b"2.5"):
                with socket.create_connection(("127.0.0.1",
                                               sup.port)) as s:
                    s.sendall(junk)
            # the valid request still lands, split across two segments
            with socket.create_connection(("127.0.0.1", sup.port)) as s:
                s.sendall(b" ")
                time.sleep(0.1)
                s.sendall(b"2\n")
            deadline = time.time() + 10
            while time.time() < deadline and \
                    log.read_text().count("\n") < 2:
                time.sleep(0.1)
            assert log.read_text() == "np=4\nnp=2\n"
            assert sup.restarts == 1
            assert sup._exit_code == 0  # junk never tripped the error path
        finally:
            sup.shutdown()

    def test_graceful_restart_on_preempted_exit(self):
        """PREEMPTED_EXIT_CODE restarts with the SAME slots (the machine
        went away; the allocation did not) — no shrink, unlike
        auto_shrink_rc."""
        from horovod_tpu.common.exceptions import PREEMPTED_EXIT_CODE

        class _ExitedProc:
            def __init__(self, rc):
                self._rc = rc
                self.pid = 4242

            def wait(self, timeout=None):
                return self._rc

            def poll(self):
                return self._rc

        codes = [PREEMPTED_EXIT_CODE, PREEMPTED_EXIT_CODE, 0]
        calls = []

        def runner(argv):
            calls.append(list(argv))
            return _ExitedProc(codes.pop(0))

        sup = ElasticSupervisor(
            "a:2,b:2", ["job", "{np}", "{bpa}", "{restart}"],
            ports=(0,), verbose=0, runner=runner,
            graceful_restart_rc=PREEMPTED_EXIT_CODE)
        try:
            sup.start()
            assert sup.wait(poll_s=0.01) == 0
        finally:
            sup.shutdown()
        assert sup.restarts == 2
        assert sup.current_total == 4  # never shrank
        assert [c[1] for c in calls] == ["4", "4", "4"]  # same np each time
        assert [c[3] for c in calls] == ["0", "1", "2"]  # restart ordinal

    def test_graceful_restart_bounded_by_max_restarts(self):
        from horovod_tpu.common.exceptions import PREEMPTED_EXIT_CODE

        class _ExitedProc:
            pid = 4242

            def wait(self, timeout=None):
                return PREEMPTED_EXIT_CODE

            def poll(self):
                return PREEMPTED_EXIT_CODE

        sup = ElasticSupervisor(
            "a:2", ["job"], ports=(0,), verbose=0,
            runner=lambda argv: _ExitedProc(),
            graceful_restart_rc=PREEMPTED_EXIT_CODE, max_restarts=3)
        try:
            sup.start()
            # a job that ALWAYS exits preempted stops after max_restarts
            assert sup.wait(poll_s=0.01) == PREEMPTED_EXIT_CODE
        finally:
            sup.shutdown()
        assert sup.restarts == 3
