"""Programmatic launcher + elastic supervisor tests (reference
test_spark.py:51-110 for run(fn); submitjob.py semantics for elasticity)."""

import socket
import sys
import time

import pytest

from horovod_tpu.run.elastic import ElasticSupervisor, shrink_hosts
from horovod_tpu.run.hosts import HostSlots, parse_hosts
from horovod_tpu.run.launch import run


class TestProgrammaticRun:
    """run(fn) happy path / args / failure (test_spark.py test_happy_run
    parity). Functions are defined as closures so cloudpickle ships them by
    value, as Spark closures are shipped in the reference."""

    def test_happy_run(self):
        def fn():
            import os
            return (int(os.environ["HVD_PROCESS_ID"]),
                    int(os.environ["HVD_NUM_PROC"]))

        assert run(fn, num_proc=2) == [(0, 2), (1, 2)]

    def test_args_kwargs(self):
        def fn(a, b, scale=1):
            import os
            return (a + b) * scale + int(os.environ["HVD_PROCESS_ID"])

        assert run(fn, args=(10, 5), kwargs={"scale": 2},
                   num_proc=2) == [30, 31]

    def test_worker_exception_propagates(self):
        def fn():
            import os
            if os.environ["HVD_PROCESS_ID"] == "1":
                raise ValueError("rank 1 exploded")
            return "ok"

        with pytest.raises(RuntimeError, match="rank 1 exploded"):
            run(fn, num_proc=2)

    def test_timeout(self):
        with pytest.raises(Exception, match="[Tt]imed out"):
            run(time.sleep, args=(60,), num_proc=1, start_timeout_s=2.0)


class TestShrinkHosts:
    def test_simple_removal(self):
        hosts = parse_hosts("a:4,b:4")
        new, total = shrink_hosts(hosts, 4, 8)
        assert total == 4 and new == [HostSlots("a", 4)]

    def test_divisibility_forces_extra_removal(self):
        # 8 slots, remove 3 -> 5, but 8 % 5 != 0 -> shrink to 4 (bpa 2)
        hosts = parse_hosts("a:4,b:4")
        new, total = shrink_hosts(hosts, 3, 8)
        assert total == 4
        assert sum(h.slots for h in new) == 4

    def test_removal_from_last_host_first(self):
        hosts = parse_hosts("a:2,b:2")
        new, total = shrink_hosts(hosts, 2, 4)
        assert new == [HostSlots("a", 2)]

    def test_impossible_raises(self):
        with pytest.raises(ValueError):
            shrink_hosts(parse_hosts("a:2"), 2, 2)


class TestElasticSupervisor:
    def test_restart_on_slot_removal(self, tmp_path):
        """E2E: job logs {np},{bpa}; removing slots restarts it with the
        rescaled values (submitjob.py:163-204)."""
        log = tmp_path / "runs.log"
        script = tmp_path / "job.py"
        script.write_text(
            "import sys, time\n"
            "open(sys.argv[1], 'a').write(sys.argv[2] + '\\n')\n"
            "time.sleep(60)\n")
        sup = ElasticSupervisor(
            "localhost:4",
            [sys.executable, str(script), str(log), "np={np},bpa={bpa}"],
            ports=tuple(range(15100, 15110)))
        sup.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not log.exists():
                time.sleep(0.1)
            assert log.read_text() == "np=4,bpa=1\n"

            # surrender 2 slots over TCP, as `echo 2 | nc` would
            with socket.create_connection(("127.0.0.1", sup.port)) as s:
                s.sendall(b"2")
            deadline = time.time() + 10
            while time.time() < deadline and \
                    log.read_text().count("\n") < 2:
                time.sleep(0.1)
            assert log.read_text() == "np=4,bpa=1\nnp=2,bpa=2\n"
            assert sup.restarts == 1
        finally:
            sup.shutdown()

    def test_wait_returns_job_exit_code(self):
        sup = ElasticSupervisor(
            "localhost:2", [sys.executable, "-c", "import sys; sys.exit(3)"],
            ports=tuple(range(15110, 15120)), verbose=0)
        sup.start()
        assert sup.wait(poll_s=0.1) == 3
