"""mpirun migration path (run/mpi.py): `mpirun -np N python train.py`
must work with ZERO extra env — rank 0 publishes the jax.distributed
rendezvous through the filesystem, keyed by the launcher's job id
(reference parity: run/run.py:458-481 jobs need nothing beyond mpirun's
own environment). mpirun is emulated by exporting the exact env it sets
(OMPI_COMM_WORLD_*), which is all the code under test reads."""

import json
import os
import subprocess
import sys
import time

import pytest

_WORKER = r"""
import numpy as np
import horovod_tpu as hvd
hvd.init()
out = hvd.allreduce(np.full((3,), float(hvd.process_rank()) + 1.0,
                            np.float32), average=False)
print("RESULT", hvd.process_rank(), hvd.process_count(),
      float(np.asarray(out)[0]), flush=True)
hvd.shutdown()
"""


class TestMpirunAutoRendezvous:
    def test_two_ranks_zero_extra_env(self, tmp_path):
        """Two processes with only mpirun's own env (no HVD_*) must form
        the job and allreduce correctly."""
        env_base = {k: v for k, v in os.environ.items()
                    if not k.startswith(("HVD_", "OMPI_", "PMI_"))}
        env_base.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "HVD_RENDEZVOUS_DIR": str(tmp_path),
            # per-job id mpirun exports to every rank
            "OMPI_MCA_orte_hnp_uri": "666.0;tcp://10.0.0.1:12345",
            "OMPI_COMM_WORLD_SIZE": "2",
        })
        procs = []
        for rank in range(2):
            env = dict(env_base)
            env["OMPI_COMM_WORLD_RANK"] = str(rank)
            # mpirun also always exports these (jax's OMPI cluster
            # detection reads LOCAL_RANK)
            env["OMPI_COMM_WORLD_LOCAL_RANK"] = str(rank)
            env["OMPI_COMM_WORLD_LOCAL_SIZE"] = "2"
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
                cwd=os.path.dirname(os.path.dirname(__file__))))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=180)
            assert p.returncode == 0, out
            outs.append(out)
        for rank, out in enumerate(outs):
            line = [l for l in out.splitlines()
                    if l.startswith("RESULT")][0].split()
            assert line[1:] == [str(rank), "2", "3.0"], out
        # rank 0 cleaned its rendezvous file up at exit
        time.sleep(0.2)
        assert not [f for f in os.listdir(tmp_path)
                    if f.startswith("hvd_mpi_rdzv_")]

    def test_detect_and_key(self, monkeypatch):
        from horovod_tpu.run import mpi as mpi_compat
        for k in ("OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK",
                  "PMI_SIZE", "PMI_RANK", "SLURM_NTASKS",
                  "SLURM_STEP_NUM_TASKS", "SLURM_PROCID",
                  "OMPI_MCA_orte_hnp_uri", "PMIX_NAMESPACE", "PMI_JOBID",
                  "SLURM_JOB_ID"):
            monkeypatch.delenv(k, raising=False)
        assert mpi_compat.detect_mpi_world() is None
        # sbatch exports SLURM_NTASKS even to a single batch-script
        # process (no srun): must NOT be treated as a multi-rank launch
        monkeypatch.setenv("SLURM_NTASKS", "4")
        monkeypatch.setenv("SLURM_PROCID", "0")
        assert mpi_compat.detect_mpi_world() is None
        # srun sets the per-step task count: that IS a multi-rank launch
        monkeypatch.setenv("SLURM_STEP_NUM_TASKS", "4")
        monkeypatch.setenv("SLURM_PROCID", "3")
        assert mpi_compat.detect_mpi_world() == (4, 3)
        monkeypatch.delenv("SLURM_STEP_NUM_TASKS")
        monkeypatch.delenv("SLURM_NTASKS")
        monkeypatch.setenv("PMI_SIZE", "4")
        monkeypatch.setenv("PMI_RANK", "3")
        assert mpi_compat.detect_mpi_world() == (4, 3)
        # no job-id env: fallback key, flagged non-unique
        key, unique = mpi_compat._job_key()
        assert not unique
        monkeypatch.setenv("SLURM_JOB_ID", "1234")
        key2, unique2 = mpi_compat._job_key()
        assert unique2 and key2 != key

    def test_stale_rendezvous_file_rejected(self, tmp_path, monkeypatch):
        """A leftover file from a crashed previous run (same key, same
        size, old timestamp) must not be trusted."""
        from horovod_tpu.run import mpi as mpi_compat
        monkeypatch.setenv("HVD_RENDEZVOUS_DIR", str(tmp_path))
        monkeypatch.setenv("SLURM_JOB_ID", "zzz")
        key, _ = mpi_compat._job_key()
        stale = {"addr": "10.9.9.9:1", "size": 2,
                 "created": time.time() - 3600}
        with open(mpi_compat._rendezvous_path(key), "w") as f:
            json.dump(stale, f)
        with pytest.raises(RuntimeError, match="no published"):
            mpi_compat.auto_rendezvous(2, 1, timeout_s=1.0)
