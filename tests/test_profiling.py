"""Device-trace summarization (utils/profiling.py): aggregation,
filtering of host-side spans, group totals, and file discovery."""

import gzip
import json

import pytest

from horovod_tpu.utils import profiling


def _write_trace(tmp_path, events, gz=True):
    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    payload = json.dumps({"traceEvents": events})
    p = d / ("t.trace.json.gz" if gz else "t.trace.json")
    if gz:
        with gzip.open(p, "wt") as f:
            f.write(payload)
    else:
        p.write_text(payload)
    return tmp_path


def _ev(name, dur, **args):
    e = {"ph": "X", "name": name, "dur": dur, "ts": 0}
    if args:
        e["args"] = args
    return e


class TestSummarizeTrace:
    def test_aggregates_and_filters(self, tmp_path):
        root = _write_trace(tmp_path, [
            _ev("fusion.1", 1000, long_name="%fusion.1 = f32[8]"),
            _ev("fusion.1", 500),
            _ev("fusion.2", 2000),
            _ev("attn.3", 4000),
            _ev("$python_span", 99999),        # host-side: excluded
            _ev("jit_step(123)", 99999),       # dispatch wrapper: excluded
            _ev("2", 99999),                   # step-group lane: excluded
            {"ph": "M", "name": "meta"},       # not a complete event
        ])
        s = profiling.summarize_trace(str(root))
        by_name = {r.name: r for r in s.rows}
        assert set(by_name) == {"fusion.1", "fusion.2", "attn.3"}
        assert by_name["fusion.1"].total_ms == pytest.approx(1.5)
        assert by_name["fusion.1"].count == 2
        assert by_name["fusion.1"].long_name.startswith("%fusion.1")
        assert s.total_ms == pytest.approx(7.5)
        # sorted by total, groups aggregate fusion.1 + fusion.2
        assert s.rows[0].name == "attn.3"
        assert dict(s.by_group()) == pytest.approx(
            {"fusion": 3.5, "attn": 4.0})

    def test_find_trace_file_errors(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="trace.json"):
            profiling.find_trace_file(str(tmp_path))

    def test_uncompressed_trace_discovered(self, tmp_path):
        root = _write_trace(tmp_path, [
            _ev("f.1", 250, long_name=""),      # args-less long_name...
            _ev("f.1", 250, long_name="%f.1"),  # ...backfilled later
        ], gz=False)
        s = profiling.summarize_trace(str(root))
        (row,) = s.rows
        assert row.total_ms == pytest.approx(0.5)
        assert row.long_name == "%f.1"

    def test_cli_main(self, tmp_path, capsys):
        root = _write_trace(tmp_path, [_ev("fusion.9", 1500)])
        profiling.main([str(root), "-n", "5"])
        out = capsys.readouterr().out
        assert "device-op total: 1.5 ms" in out
        assert "fusion.9" in out
