"""Device-trace summarization (utils/profiling.py): aggregation,
filtering of host-side spans, group totals, and file discovery."""

import gzip
import json

import pytest

from horovod_tpu.utils import profiling


def _write_trace(tmp_path, events, gz=True):
    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    payload = json.dumps({"traceEvents": events})
    p = d / ("t.trace.json.gz" if gz else "t.trace.json")
    if gz:
        with gzip.open(p, "wt") as f:
            f.write(payload)
    else:
        p.write_text(payload)
    return tmp_path


def _ev(name, dur, ts=0, pid=1, tid=1, **args):
    e = {"ph": "X", "name": name, "dur": dur, "ts": ts,
         "pid": pid, "tid": tid}
    if args:
        e["args"] = args
    return e


class TestSummarizeTrace:
    def test_aggregates_and_filters(self, tmp_path):
        root = _write_trace(tmp_path, [
            _ev("fusion.1", 1000, long_name="%fusion.1 = f32[8]"),
            _ev("fusion.1", 500),
            _ev("fusion.2", 2000),
            _ev("attn.3", 4000),
            _ev("$python_span", 99999),        # host-side: excluded
            _ev("jit_step(123)", 99999),       # dispatch wrapper: excluded
            _ev("2", 99999),                   # step-group lane: excluded
            {"ph": "M", "name": "meta"},       # not a complete event
        ])
        s = profiling.summarize_trace(str(root))
        by_name = {r.name: r for r in s.rows}
        assert set(by_name) == {"fusion.1", "fusion.2", "attn.3"}
        assert by_name["fusion.1"].total_ms == pytest.approx(1.5)
        assert by_name["fusion.1"].count == 2
        assert by_name["fusion.1"].long_name.startswith("%fusion.1")
        assert s.total_ms == pytest.approx(7.5)
        # sorted by total, groups aggregate fusion.1 + fusion.2
        assert s.rows[0].name == "attn.3"
        assert dict(s.by_group()) == pytest.approx(
            {"fusion": 3.5, "attn": 4.0})

    def test_find_trace_file_errors(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="trace.json"):
            profiling.find_trace_file(str(tmp_path))

    def test_uncompressed_trace_discovered(self, tmp_path):
        root = _write_trace(tmp_path, [
            _ev("f.1", 250, long_name=""),      # args-less long_name...
            _ev("f.1", 250, long_name="%f.1"),  # ...backfilled later
        ], gz=False)
        s = profiling.summarize_trace(str(root))
        (row,) = s.rows
        assert row.total_ms == pytest.approx(0.5)
        assert row.long_name == "%f.1"

    def test_cli_main(self, tmp_path, capsys):
        root = _write_trace(tmp_path, [_ev("fusion.9", 1500)])
        profiling.main([str(root), "-n", "5"])
        out = capsys.readouterr().out
        assert "device-op total: 1.5 ms" in out
        assert "fusion.9" in out

    def test_retains_lane_intervals_and_names(self, tmp_path):
        root = _write_trace(tmp_path, [
            {"ph": "M", "name": "thread_name", "pid": 1, "tid": 7,
             "args": {"name": "TPU core 0 compute"}},
            _ev("fusion.1", 1000, ts=500, pid=1, tid=7),
            _ev("all-reduce.2", 2000, ts=1000, pid=1, tid=9),
        ])
        s = profiling.summarize_trace(str(root))
        assert s.lane_names == {"1/7": "TPU core 0 compute"}
        by_name = {e.name: e for e in s.events}
        assert by_name["fusion.1"].lane == "1/7"
        assert by_name["fusion.1"].start_ms == pytest.approx(0.5)
        assert by_name["fusion.1"].end_ms == pytest.approx(1.5)
        assert by_name["all-reduce.2"].lane == "1/9"


class TestClassifyOp:
    # representative XLA HLO / Pallas custom-call names → expected class;
    # pins the _OP_CLASSES table against silent rot
    CASES = [
        ("%all-reduce.1", "", "collective"),
        ("all-reduce-start.7", "", "collective"),
        ("reduce-scatter.3", "", "collective"),
        ("all-gather.12", "", "collective"),
        ("all-to-all.2", "", "collective"),
        ("%dot.42", "", "matmul"),
        ("dot_general.5", "", "matmul"),
        ("convolution.8", "", "matmul"),
        ("custom-call.3", "%custom-call.3 = ... fwd_kernel", "flash_fwd"),
        ("custom-call.4", "%custom-call.4 = ... dq_kernel", "flash_dq"),
        ("custom-call.5", "%custom-call.5 = ... dkv_kernel", "flash_dkv"),
        ("copy.9", "", "copy"),
        ("transpose.1", "", "copy"),
        ("dynamic-update-slice.6", "", "copy"),
        ("bitcast.2", "", "copy"),
        # note: bitcast-CONVert / input_CONCATENATE_fusion would land in
        # matmul/copy via substring first-match — the table is ordered,
        # not exact; keep needles honest when extending it
        ("fusion.123", "", "fusion"),
        ("loop_add_fusion.4", "", "fusion"),
        ("output_tanh_fusion", "", "fusion"),
        ("broadcast.77", "", "other"),
        ("rng-bit-generator.1", "", "other"),
    ]

    @pytest.mark.parametrize("name,long_name,expected", CASES)
    def test_table(self, name, long_name, expected):
        row = profiling.OpRow(name, name.split(".")[0], 1.0, 1, long_name)
        assert profiling.classify_op(row) == expected

    def test_first_match_wins_over_long_name(self):
        # a fusion whose long_name mentions a dot: collective/flash
        # classes are checked first, then matmul — "dot" in the
        # long_name promotes it to matmul before the fusion fallback
        row = profiling.OpRow("fusion.1", "fusion", 1.0, 1,
                              "%fusion.1 = fusion(dot.3)")
        assert profiling.classify_op(row) == "matmul"


class TestOverlapAccounting:
    def _mixed_root(self, tmp_path):
        # lane 1/1 = compute, lane 1/2 = async collective stream.
        # compute busy [0,4)ms and [6,8)ms; comm busy [2,7)ms
        # → hidden = [2,4)+[6,7) = 3ms, exposed = [4,6) = 2ms
        return _write_trace(tmp_path, [
            _ev("fusion.1", 4000, ts=0, tid=1),
            _ev("dot.2", 2000, ts=6000, tid=1),
            _ev("all-reduce.3", 5000, ts=2000, tid=2),
        ])

    def test_hidden_vs_exposed(self, tmp_path):
        s = profiling.summarize_trace(str(self._mixed_root(tmp_path)))
        ov = profiling.overlap_accounting(s)
        assert ov["comm_ms_per_step"] == pytest.approx(5.0)
        assert ov["compute_ms_per_step"] == pytest.approx(6.0)
        assert ov["hidden_comm_ms"] == pytest.approx(3.0)
        assert ov["exposed_comm_ms"] == pytest.approx(2.0)
        assert ov["overlap_frac"] == pytest.approx(0.6)
        assert ov["span_ms_per_step"] == pytest.approx(8.0)
        lanes = {l["lane"]: l for l in ov["lanes"]}
        assert lanes["1/1"]["busy_ms_per_step"] == pytest.approx(6.0)
        assert lanes["1/1"]["busy_frac"] == pytest.approx(0.75)
        assert lanes["1/2"]["busy_ms_per_step"] == pytest.approx(5.0)
        assert lanes["1/2"]["busy_frac"] == pytest.approx(0.625)

    def test_fully_hidden_comm(self, tmp_path):
        root = _write_trace(tmp_path, [
            _ev("fusion.1", 8000, ts=0, tid=1),
            _ev("all-reduce.2", 3000, ts=2000, tid=2),
        ])
        ov = profiling.overlap_accounting(str(root))
        assert ov["hidden_comm_ms"] == pytest.approx(3.0)
        assert ov["exposed_comm_ms"] == pytest.approx(0.0)
        assert ov["overlap_frac"] == pytest.approx(1.0)

    def test_fully_exposed_comm_and_steps(self, tmp_path):
        # comm strictly after compute, over 2 steps → per-step halves
        root = _write_trace(tmp_path, [
            _ev("fusion.1", 4000, ts=0, tid=1),
            _ev("all-reduce.2", 6000, ts=4000, tid=2),
        ])
        ov = profiling.overlap_accounting(str(root), steps=2)
        assert ov["hidden_comm_ms"] == pytest.approx(0.0)
        assert ov["exposed_comm_ms"] == pytest.approx(3.0)
        assert ov["overlap_frac"] == pytest.approx(0.0)
        assert ov["comm_ms_per_step"] == pytest.approx(3.0)

    def test_no_comm_gives_none_frac(self, tmp_path):
        root = _write_trace(tmp_path, [_ev("fusion.1", 1000, tid=1)])
        ov = profiling.overlap_accounting(str(root))
        assert ov["comm_ms_per_step"] == pytest.approx(0.0)
        assert ov["overlap_frac"] is None

    def test_overlapping_same_class_intervals_union(self, tmp_path):
        # two overlapping collectives must not double-count
        root = _write_trace(tmp_path, [
            _ev("all-reduce.1", 4000, ts=0, tid=2),
            _ev("all-reduce.2", 4000, ts=2000, tid=3),
        ])
        ov = profiling.overlap_accounting(str(root))
        assert ov["comm_ms_per_step"] == pytest.approx(6.0)
        assert ov["exposed_comm_ms"] == pytest.approx(6.0)

    def test_rows_only_summary_returns_none(self):
        rows = [profiling.OpRow("fusion.1", "fusion", 1.0, 1, "")]
        assert profiling.overlap_accounting(
            profiling.TraceSummary(rows)) is None


class TestProfileDecomposition:
    def test_classes_wall_and_overlap(self, tmp_path):
        root = _write_trace(tmp_path, [
            _ev("fusion.1", 4000, ts=0, tid=1),
            _ev("all-reduce.3", 5000, ts=2000, tid=2),
        ])
        dec = profiling.profile_decomposition(str(root), wall_ms=10.0)
        assert dec["device_ms_per_step"] == pytest.approx(9.0)
        assert dec["wall_ms_per_step"] == pytest.approx(10.0)
        assert dec["residual_ms_per_step"] == pytest.approx(1.0)
        assert dec["device_busy_frac"] == pytest.approx(0.9)
        by_cls = {c["class"]: c for c in dec["classes"]}
        assert by_cls["collective"]["ms_per_step"] == pytest.approx(5.0)
        assert by_cls["fusion"]["ms_per_step"] == pytest.approx(4.0)
        assert dec["overlap"]["hidden_comm_ms"] == pytest.approx(2.0)
        assert dec["overlap"]["exposed_comm_ms"] == pytest.approx(3.0)

    def test_wall_ms_zero_guarded(self, tmp_path):
        # wall_ms=0 used to emit residual=-device_ms with frac None;
        # now both are None and the wall is reported as 0
        root = _write_trace(tmp_path, [_ev("fusion.1", 1000)])
        dec = profiling.profile_decomposition(str(root), wall_ms=0.0)
        assert dec["wall_ms_per_step"] == 0.0
        assert dec["residual_ms_per_step"] is None
        assert dec["device_busy_frac"] is None

    def test_wall_ms_none_omits_wall_keys(self, tmp_path):
        root = _write_trace(tmp_path, [_ev("fusion.1", 1000)])
        dec = profiling.profile_decomposition(str(root))
        assert "wall_ms_per_step" not in dec
        assert "residual_ms_per_step" not in dec

    def test_cli_overlap_flag(self, tmp_path, capsys):
        root = _write_trace(tmp_path, [
            _ev("fusion.1", 4000, ts=0, tid=1),
            _ev("all-reduce.2", 2000, ts=1000, tid=2),
        ])
        profiling.main([str(root), "--overlap"])
        out = json.loads(capsys.readouterr().out)
        assert out["hidden_comm_ms"] == pytest.approx(2.0)
