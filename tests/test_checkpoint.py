"""Checkpoint save/restore round-trip + resume consistency
(reference app-level pattern, examples/pytorch_mnist.py:175-195), plus
the checkpoint plane (docs/checkpoint.md): async double-buffered saves,
sharded per-rank writes with a single manifest commit point, fail-loud
integrity, M->N reshard, retention GC, and the save-interruption
torture matrix."""

import os
import threading

import numpy as np
import pytest


def test_save_restore_roundtrip(hvd, tmp_path):
    import jax.numpy as jnp
    from horovod_tpu.utils import checkpoint

    tree = {"w": jnp.arange(6.0).reshape(2, 3),
            "nested": {"b": jnp.ones(4), "s": jnp.float32(2.5)}}
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, tree, step=7)
    assert checkpoint.exists(path)
    assert checkpoint.latest_step(path) == 7
    restored, step = checkpoint.restore(path, like=tree)
    assert step == 7
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(np.asarray(restored["nested"]["b"]),
                               np.ones(4))


def test_save_is_atomic_overwrite(hvd, tmp_path):
    from horovod_tpu.utils import checkpoint
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, {"x": np.zeros(2)}, step=1)
    checkpoint.save(path, {"x": np.ones(2)}, step=2)
    restored, step = checkpoint.restore(path, like={"x": np.zeros(2)})
    assert step == 2
    np.testing.assert_allclose(restored["x"], np.ones(2))
    # no leftover temp dirs
    leftovers = [d for d in os.listdir(tmp_path) if d.startswith(".ckpt-tmp")]
    assert not leftovers


def test_restore_then_broadcast(hvd, tmp_path):
    """resume flow: restore on all, broadcast from rank 0 for consistency."""
    import jax.numpy as jnp
    from horovod_tpu.utils import checkpoint

    params = {"k": jnp.full((4,), 3.0)}
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, params, step=3)
    restored, _ = checkpoint.restore(path, like=params)
    synced = hvd.broadcast_parameters(restored)
    np.testing.assert_allclose(np.asarray(synced["k"]), np.full((4,), 3.0))


def test_restore_falls_back_to_old_after_interrupted_overwrite(hvd, tmp_path):
    """Crash between the two renames leaves <path>.old — restore must use
    it (crash-safe overwrite semantics for elastic restart)."""
    import os
    from horovod_tpu.utils import checkpoint
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, {"x": np.full(2, 1.0)}, step=1)
    # simulate the crash window: old parked, new never installed
    os.replace(path, path + ".old")
    assert checkpoint.exists(path)
    restored, step = checkpoint.restore(path, like={"x": np.zeros(2)})
    assert step == 1
    np.testing.assert_allclose(restored["x"], np.full(2, 1.0))


def test_latest_step_reads_old_fallback(hvd, tmp_path):
    """Regression: latest_step() used to open <path>/manifest.json even
    when only <path>.old survived the crash window exists() accepts —
    a FileNotFoundError exactly when the caller is deciding whether it
    can resume."""
    from horovod_tpu.utils import checkpoint
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, {"x": np.zeros(2)}, step=9)
    os.replace(path, path + ".old")
    assert checkpoint.exists(path)
    assert checkpoint.latest_step(path) == 9
    assert checkpoint.latest_step(str(tmp_path / "nothing")) is None


def test_restore_like_mismatch_fails_loud(hvd, tmp_path):
    """A model that changed shape between save and resume must refuse to
    restore, naming the differing leaves — not silently unflatten a
    scrambled tree."""
    from horovod_tpu.common.exceptions import CheckpointError
    from horovod_tpu.utils import checkpoint
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, {"w": np.zeros(2), "b": np.ones(3)}, step=1)
    with pytest.raises(CheckpointError, match="mismatch") as ei:
        checkpoint.restore(path, like={"w": np.zeros(2),
                                       "extra_head": np.zeros(4)})
    assert "extra_head" in str(ei.value)
    assert "b" in str(ei.value)
    # like=None stays the raw-dict escape hatch
    raw, step = checkpoint.restore(path)
    assert step == 1 and len(raw) == 2


# ---------------------------------------------------------------------------
# CheckpointManager (format 2)
# ---------------------------------------------------------------------------

TREE = {"w": np.arange(6.0).reshape(2, 3),
        "opt": {"m": np.ones(4), "v": np.full(4, 0.5)},
        "step_scale": np.float32(1.5)}


def _bump(tree, k):
    return {key: ({kk: vv + k for kk, vv in val.items()}
                  if isinstance(val, dict) else val + k)
            for key, val in tree.items()}


def test_manager_sync_roundtrip_with_extra(hvd, tmp_path):
    from horovod_tpu.utils import checkpoint
    mgr = checkpoint.CheckpointManager(str(tmp_path / "c"), async_save=False)
    d = mgr.save(TREE, step=12, extra={"data_pos": 12, "rng": [0, 7]})
    assert d is not None and os.path.exists(os.path.join(d, "manifest.json"))
    assert mgr.latest_step() == 12
    tree, step, extra = mgr.restore(like=TREE)
    assert step == 12 and extra == {"data_pos": 12, "rng": [0, 7]}
    np.testing.assert_allclose(tree["opt"]["v"], np.full(4, 0.5))
    # module-level restore reads format 2 transparently
    tree2, step2 = checkpoint.restore(str(tmp_path / "c"), like=TREE)
    assert step2 == 12
    np.testing.assert_allclose(np.asarray(tree2["w"]), TREE["w"])
    mgr.close()


def test_manager_async_drains_and_drops_stale_snapshots(hvd, tmp_path):
    """Latest-wins buffer: the step loop never stalls on a slow disk;
    superseded snapshots are dropped and counted, the newest always
    lands."""
    from horovod_tpu.utils import checkpoint
    mgr = checkpoint.CheckpointManager(str(tmp_path / "c"), keep=0)
    assert mgr.async_save
    gate = threading.Event()
    checkpoint._FAILPOINTS["pre_shard"] = gate.wait
    try:
        mgr.save(_bump(TREE, 1), step=1)
        for s in range(2, 6):  # all queued behind the stalled writer
            mgr.save(_bump(TREE, s), step=s)
    finally:
        checkpoint._FAILPOINTS.clear()
        gate.set()
    mgr.wait(timeout=30)
    mgr.close()
    committed = sorted(checkpoint._committed_steps(str(tmp_path / "c")))
    assert committed[-1] == 5  # newest snapshot always survives
    assert 2 <= len(committed) <= 3  # stale queued ones were dropped
    tree, step, _ = checkpoint.CheckpointManager(
        str(tmp_path / "c")).restore(like=TREE)
    assert step == 5
    np.testing.assert_allclose(tree["opt"]["m"], np.ones(4) + 5)


def test_manager_retention_keeps_last_k(hvd, tmp_path):
    from horovod_tpu.utils import checkpoint
    mgr = checkpoint.CheckpointManager(str(tmp_path / "c"), keep=2,
                                       async_save=False)
    for s in (3, 7, 11, 15):
        mgr.save(_bump(TREE, s), step=s)
    mgr.close()
    assert sorted(checkpoint._committed_steps(str(tmp_path / "c"))) == \
        [11, 15]
    # restore(step=...) names the committed steps when asked for a GC'd one
    with pytest.raises(FileNotFoundError, match=r"\[11, 15\]"):
        checkpoint.restore(str(tmp_path / "c"), like=TREE, step=3)


def test_manager_sharded_save_reshards_into_any_world(hvd, tmp_path):
    """3 ranks write round-robin shards; restore reassembles the full
    tree regardless of the restore-time world size (M->N elastic
    restart)."""
    from horovod_tpu.utils import checkpoint
    root = str(tmp_path / "c")
    mgrs = [checkpoint.CheckpointManager(root, rank=r, world_size=3,
                                         async_save=False)
            for r in range(3)]
    errs = []

    def run(m):
        try:
            m.save(_bump(TREE, 2), step=4, extra={"data_pos": 4})
        except Exception as e:  # noqa: BLE001 — surfaced via errs below
            errs.append(e)

    threads = [threading.Thread(target=run, args=(m,)) for m in mgrs[1:]]
    for t in threads:
        t.start()
    mgrs[0].save(_bump(TREE, 2), step=4, extra={"data_pos": 4})
    for t in threads:
        t.join()
    assert not errs
    d = checkpoint._committed_steps(root)[4]
    shards = [f for f in os.listdir(d) if f.endswith(".npz")]
    assert len(shards) == 3  # every rank wrote its own shard
    # restore-time world size is irrelevant: any manager (or the module
    # function) reads all save-time shards
    for world in (1, 2, 5):
        mgr = checkpoint.CheckpointManager(root, rank=0, world_size=world)
        tree, step, extra = mgr.restore(like=TREE)
        assert step == 4 and extra == {"data_pos": 4}
        np.testing.assert_allclose(tree["w"], TREE["w"] + 2)
        np.testing.assert_allclose(tree["opt"]["v"], TREE["opt"]["v"] + 2)


def test_manager_commit_waits_for_all_ranks(hvd, tmp_path):
    """Rank 0 must NOT commit until every peer's manifest exists: a rank
    dying mid-save leaves the checkpoint uncommitted, not half-valid."""
    from horovod_tpu.common.exceptions import CheckpointError
    from horovod_tpu.utils import checkpoint
    root = str(tmp_path / "c")
    mgr0 = checkpoint.CheckpointManager(root, rank=0, world_size=2,
                                        async_save=False,
                                        commit_timeout_s=0.3)
    with pytest.raises(CheckpointError, match="never appeared"):
        mgr0.save(TREE, step=1)  # rank 1 never shows up
    assert not checkpoint._committed_steps(root)
    assert not checkpoint.exists(root)


def test_manager_corruption_fails_loud(hvd, tmp_path):
    from horovod_tpu.common.exceptions import CorruptCheckpointError
    from horovod_tpu.utils import checkpoint
    root = str(tmp_path / "c")
    mgr = checkpoint.CheckpointManager(root, async_save=False)
    d = mgr.save(TREE, step=2)
    shard = os.path.join(d, "rank00000.npz")
    blob = bytearray(open(shard, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # single flipped bit, same size
    with open(shard, "wb") as f:
        f.write(blob)
    with pytest.raises(CorruptCheckpointError, match="checksum"):
        mgr.restore(like=TREE)
    # truncation is caught by the recorded size before the crc pass
    with open(shard, "wb") as f:
        f.write(blob[:-10])
    with pytest.raises(CorruptCheckpointError, match="bytes"):
        mgr.restore(like=TREE)


def test_manager_verify_false_skips_checksums(hvd, tmp_path):
    """verify=False is the explicit escape hatch (trusted local disk):
    a manifest whose RECORDED crc is wrong fails verification but the
    intact data still restores when verification is skipped."""
    import json

    from horovod_tpu.common.exceptions import CorruptCheckpointError
    from horovod_tpu.utils import checkpoint
    root = str(tmp_path / "c")
    mgr = checkpoint.CheckpointManager(root, async_save=False)
    d = mgr.save(TREE, step=2)
    mpath = os.path.join(d, "manifest.json")
    manifest = json.load(open(mpath))
    manifest["files"]["rank00000.npz"]["crc"] ^= 1
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(CorruptCheckpointError, match="checksum"):
        mgr.restore(like=TREE)
    tree, step, _ = mgr.restore(like=TREE, verify=False)
    assert step == 2
    np.testing.assert_allclose(tree["w"], TREE["w"])


def test_manager_v2_like_mismatch_fails_loud(hvd, tmp_path):
    from horovod_tpu.common.exceptions import CheckpointError
    from horovod_tpu.utils import checkpoint
    mgr = checkpoint.CheckpointManager(str(tmp_path / "c"),
                                       async_save=False)
    mgr.save(TREE, step=1)
    with pytest.raises(CheckpointError, match="mismatch"):
        mgr.restore(like={"w": np.zeros((2, 3))})


def test_manager_async_writer_error_reaches_the_train_loop(hvd, tmp_path):
    """The writer thread cannot stop the job itself; its failure must
    surface on the next save()/wait()/close() call instead of rotting
    silently while the job runs on with no durability."""
    from horovod_tpu.common.exceptions import CheckpointError
    from horovod_tpu.utils import checkpoint

    def boom():
        raise OSError(28, "No space left on device")

    mgr = checkpoint.CheckpointManager(str(tmp_path / "c"))
    checkpoint._FAILPOINTS["pre_commit"] = boom
    try:
        mgr.save(TREE, step=1)
        with pytest.raises(CheckpointError, match="No space left"):
            mgr.wait(timeout=30)
    finally:
        checkpoint._FAILPOINTS.clear()
    mgr.close()


# ---------------------------------------------------------------------------
# save-interruption torture matrix (satellite of the commit protocol):
# kill the writer at EVERY failure point; restore() must always return a
# complete, checksum-valid checkpoint — the previous commit for any
# interruption before the manifest rename, the new one at/after it.
# ---------------------------------------------------------------------------

_POINTS = {  # failpoint -> step restore() must see afterwards
    "pre_shard": 1, "post_shard": 1, "pre_rank_manifest": 1,
    "post_rank_manifest": 1, "pre_commit": 1, "mid_commit": 1,
    "post_commit": 2,
}


class _Torture(RuntimeError):
    pass


@pytest.mark.parametrize("point", sorted(_POINTS))
def test_torture_save_interrupted_at_every_point(hvd, tmp_path, point):
    from horovod_tpu.utils import checkpoint
    root = str(tmp_path / "c")
    mgr = checkpoint.CheckpointManager(root, async_save=False, keep=4)
    mgr.save(_bump(TREE, 1), step=1)

    def boom():
        raise _Torture(point)

    checkpoint._FAILPOINTS[point] = boom
    try:
        with pytest.raises(_Torture):
            mgr.save(_bump(TREE, 2), step=2)
    finally:
        checkpoint._FAILPOINTS.clear()

    # the surviving checkpoint is complete and checksum-valid
    want = _POINTS[point]
    tree, step, _ = mgr.restore(like=TREE, verify=True)
    assert step == want
    np.testing.assert_allclose(tree["w"], TREE["w"] + want)
    # no torn commit: every committed dir passes full verification
    for s, d in checkpoint._committed_steps(root).items():
        checkpoint._verify_files(d, checkpoint._read_global_manifest(d))

    # recovery: the next save commits and GC clears any dead partial
    mgr.save(_bump(TREE, 3), step=3)
    tree, step, _ = mgr.restore(like=TREE, verify=True)
    assert step == 3
    committed = checkpoint._committed_steps(root)
    for name in os.listdir(root):
        if name.startswith("step-"):
            s = int(name.split("-")[1])
            assert s in committed, f"uncommitted partial {name} survived GC"
    mgr.close()
