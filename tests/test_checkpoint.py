"""Checkpoint save/restore round-trip + resume consistency
(reference app-level pattern, examples/pytorch_mnist.py:175-195)."""

import os

import numpy as np


def test_save_restore_roundtrip(hvd, tmp_path):
    import jax.numpy as jnp
    from horovod_tpu.utils import checkpoint

    tree = {"w": jnp.arange(6.0).reshape(2, 3),
            "nested": {"b": jnp.ones(4), "s": jnp.float32(2.5)}}
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, tree, step=7)
    assert checkpoint.exists(path)
    assert checkpoint.latest_step(path) == 7
    restored, step = checkpoint.restore(path, like=tree)
    assert step == 7
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(np.asarray(restored["nested"]["b"]),
                               np.ones(4))


def test_save_is_atomic_overwrite(hvd, tmp_path):
    from horovod_tpu.utils import checkpoint
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, {"x": np.zeros(2)}, step=1)
    checkpoint.save(path, {"x": np.ones(2)}, step=2)
    restored, step = checkpoint.restore(path, like={"x": np.zeros(2)})
    assert step == 2
    np.testing.assert_allclose(restored["x"], np.ones(2))
    # no leftover temp dirs
    leftovers = [d for d in os.listdir(tmp_path) if d.startswith(".ckpt-tmp")]
    assert not leftovers


def test_restore_then_broadcast(hvd, tmp_path):
    """resume flow: restore on all, broadcast from rank 0 for consistency."""
    import jax.numpy as jnp
    from horovod_tpu.utils import checkpoint

    params = {"k": jnp.full((4,), 3.0)}
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, params, step=3)
    restored, _ = checkpoint.restore(path, like=params)
    synced = hvd.broadcast_parameters(restored)
    np.testing.assert_allclose(np.asarray(synced["k"]), np.full((4,), 3.0))


def test_restore_falls_back_to_old_after_interrupted_overwrite(hvd, tmp_path):
    """Crash between the two renames leaves <path>.old — restore must use
    it (crash-safe overwrite semantics for elastic restart)."""
    import os
    from horovod_tpu.utils import checkpoint
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, {"x": np.full(2, 1.0)}, step=1)
    # simulate the crash window: old parked, new never installed
    os.replace(path, path + ".old")
    assert checkpoint.exists(path)
    restored, step = checkpoint.restore(path, like={"x": np.zeros(2)})
    assert step == 1
    np.testing.assert_allclose(restored["x"], np.full(2, 1.0))
