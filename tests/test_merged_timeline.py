"""Merged timeline: Horovod host spans and the XLA device trace in ONE
Chrome-trace file on a shared clock base (the reference shows comm
activity inside op execution in one view — timeline.h:80-125,
mpi_operations.cc:35-62; here the device half comes from jax.profiler).
"""

import json

import numpy as np
import pytest


@pytest.fixture
def hvd_timeline(monkeypatch, tmp_path):
    path = tmp_path / "timeline.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    import horovod_tpu as hvd_mod
    hvd_mod.init()
    yield hvd_mod, path
    hvd_mod.shutdown()


class TestMergedTimeline:
    def test_capture_writes_one_file_with_both_event_classes(
            self, hvd_timeline, tmp_path):
        hvd, _ = hvd_timeline
        from horovod_tpu.utils import merged_timeline

        out = tmp_path / "merged.json"
        with merged_timeline.capture(str(out),
                                     profiler_dir=str(tmp_path / "prof")):
            for i in range(3):
                hvd.allreduce(np.full((8, 4), float(i)),
                              average=False, name=f"mt.grad{i}")

        data = json.loads(out.read_text())
        events = data["traceEvents"]
        # host spans from the Horovod timeline…
        hvd_spans = [e for e in events
                     if e.get("pid", 0) >= merged_timeline._HVD_PID_BASE]
        names = {e.get("name") for e in hvd_spans}
        assert "NEGOTIATE_ALLREDUCE" in names
        assert "ALLREDUCE" in names
        # …and complete profiler events from the XLA capture, in the
        # same file, on re-based non-negative timestamps
        prof_events = [e for e in events
                       if e.get("pid", 0) < merged_timeline._HVD_PID_BASE
                       and e.get("ph") == "X"]
        assert prof_events, "no device-trace events in the merged file"
        assert all(e["ts"] >= 0 for e in events if "ts" in e)

    def test_clocks_align_within_the_session(self, hvd_timeline, tmp_path):
        """The collective's host span and the profiler's window must land
        in the same neighborhood — not seconds apart — or the merge's
        clock math is wrong."""
        hvd, _ = hvd_timeline
        from horovod_tpu.utils import merged_timeline

        out = tmp_path / "merged.json"
        with merged_timeline.capture(str(out),
                                     profiler_dir=str(tmp_path / "prof")):
            hvd.allreduce(np.ones((8, 4)), average=False, name="mt.align")

        events = json.loads(out.read_text())["traceEvents"]
        hvd_ts = [e["ts"] for e in events
                  if e.get("pid", 0) >= merged_timeline._HVD_PID_BASE
                  and "ts" in e]
        prof_ts = [e["ts"] for e in events
                   if e.get("pid", 0) < merged_timeline._HVD_PID_BASE
                   and "ts" in e]
        assert hvd_ts and prof_ts
        # both streams cover one short session: their extents overlap to
        # within a generous second
        assert min(hvd_ts) < max(prof_ts) + 1e6
        assert min(prof_ts) < max(hvd_ts) + 1e6

    def test_capture_without_timeline_raises(self, hvd, tmp_path):
        from horovod_tpu.utils import merged_timeline
        with pytest.raises(RuntimeError, match="HOROVOD_TIMELINE"):
            with merged_timeline.capture(str(tmp_path / "m.json")):
                pass

    def test_body_exception_propagates_unmasked(self, hvd_timeline,
                                                tmp_path):
        """A failure inside the traced body must surface as itself — not
        be replaced by a merge error over the aborted capture."""
        hvd, _ = hvd_timeline
        from horovod_tpu.utils import merged_timeline

        with pytest.raises(ZeroDivisionError):
            with merged_timeline.capture(str(tmp_path / "m.json")):
                1 / 0
        assert not (tmp_path / "m.json").exists()

    def test_merge_combines_all_per_host_trace_files(self, tmp_path):
        """Multi-host captures write one <host>.trace.json.gz per host;
        the merge must include every host's events, not an arbitrary
        first file."""
        import gzip

        from horovod_tpu.utils import merged_timeline

        tl = tmp_path / "t.json"
        tl.write_text(
            '[\n{"name": "clock_sync", "ph": "M", "pid": 0, '
            '"args": {"epoch_us_at_ts0": 1000000}},\n'
            '{"name": "ALLREDUCE", "ph": "B", "pid": 1, "ts": 5},\n')
        session = tmp_path / "plugins" / "profile" / "2026_01_01"
        session.mkdir(parents=True)
        for host in ("hosta", "hostb"):
            with gzip.open(session / f"{host}.trace.json.gz", "wt") as f:
                json.dump({"traceEvents": [
                    {"name": f"op-{host}", "ph": "X", "pid": 7,
                     "ts": 1.0, "dur": 2.0}]}, f)
        out = tmp_path / "m.json"
        merged_timeline.merge(str(tl), str(tmp_path), str(out),
                              profiler_epoch_us=1000100.0)
        names = {e.get("name") for e in
                 json.loads(out.read_text())["traceEvents"]}
        assert {"op-hosta", "op-hostb", "ALLREDUCE"} <= names

    def test_merge_rejects_presync_timeline(self, tmp_path):
        from horovod_tpu.utils import merged_timeline
        old = tmp_path / "old.json"
        old.write_text('[\n{"name": "ALLREDUCE", "ph": "B", "pid": 1, '
                       '"ts": 5},\n')
        with pytest.raises(ValueError, match="clock_sync"):
            merged_timeline.merge(str(old), str(tmp_path),
                                  str(tmp_path / "m.json"))
