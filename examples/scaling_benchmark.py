"""Scaling-efficiency sweep — the reference's headline metric
(docs/benchmarks.md:6-7: total_imgs_per_sec(N) / (N * imgs_per_sec(1)),
90% for Inception V3 / ResNet-101 at 512 GPUs) measured in one process
over growing device counts.

Weak scaling: per-worker batch is fixed, so perfect scaling is a flat
img/sec/worker line; efficiency(N) = rate_per_worker(N) /
rate_per_worker(baseline), where baseline is the smallest count in the
sweep (1 unless --device-counts says otherwise — the output labels it).
Runs on all local TPU chips or the virtual CPU mesh:

    python examples/scaling_benchmark.py                   # all local chips
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/scaling_benchmark.py --model resnet18 --batch-size 4
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

import horovod_tpu as hvd
from horovod_tpu import models

from bench_common import build_step, positive_int, timed_rates


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=sorted(models.names()) + ["transformer"])
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-worker batch (fixed across the sweep)")
    p.add_argument("--device-counts", default=None,
                   help="comma-separated, e.g. 1,2,4,8 "
                        "(default: powers of two up to all devices)")
    p.add_argument("--num-warmup-batches", type=int, default=5)
    p.add_argument("--num-iters", type=positive_int, default=3)
    p.add_argument("--num-batches-per-iter", type=positive_int, default=10)
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--fp16-allreduce", action="store_true")
    return p.parse_args()


def measure(args, n_devices):
    """samples/sec per worker (images, or sequences for the flagship
    transformer) on the first n_devices local devices."""
    from bench_common import build_transformer_step

    hvd.init(devices=jax.devices()[:n_devices])
    batch = args.batch_size * n_devices
    if args.model == "transformer":
        from horovod_tpu.parallel import mesh as mesh_mod
        if args.fp16_allreduce or args.image_size is not None:
            raise SystemExit(
                "--fp16-allreduce/--image-size apply to the image zoo "
                "only; the transformer step has its own recipe "
                "(bench_common.build_transformer_step)")
        on_tpu = jax.devices()[0].platform == "tpu"
        seq = 1024 if on_tpu else 64
        # the transformer's param specs name dp/tp/sp/ep axes, so it
        # needs the named mesh, not init()'s default 1-D 'hvd' mesh
        dp_mesh = mesh_mod.build_mesh(
            dp=n_devices, devices=jax.devices()[:n_devices])
        step, params, opt_state, batch_data, _ = build_transformer_step(
            dp_mesh, batch, seq, on_tpu=on_tpu)
    else:
        step, params, opt_state, batch_data = build_step(
            args.model, hvd.mesh(), batch, args.image_size,
            fp16_allreduce=args.fp16_allreduce)
    rates = timed_rates(step, params, opt_state, batch_data, batch,
                        args.num_warmup_batches, args.num_iters,
                        args.num_batches_per_iter)
    hvd.shutdown()
    return float(np.mean(rates)) / n_devices


def main():
    args = parse_args()
    n_avail = len(jax.devices())
    if args.device_counts:
        try:
            counts = sorted({positive_int(c)
                             for c in args.device_counts.split(",")})
        except ValueError as e:
            raise SystemExit(f"--device-counts: {e}")
        bad = [c for c in counts if c > n_avail]
        if bad:
            raise SystemExit(f"asked for {bad} devices, have {n_avail}")
    else:
        counts, c = [], 1
        while c <= n_avail:
            counts.append(c)
            c *= 2
    if args.image_size is None and args.model != "transformer":
        on_tpu = jax.devices()[0].platform == "tpu"
        args.image_size = models.image_size(args.model) if on_tpu else 64

    base = counts[0]
    shape_note = ("seq 1024 (64 on cpu)" if args.model == "transformer"
                  else f"image {args.image_size}")
    print(f"Model: {args.model}, batch {args.batch_size}/worker, "
          f"{shape_note}, devices {counts} "
          f"(efficiency baseline: {base} worker(s))")
    rate_unit = "seq/sec" if args.model == "transformer" else "img/sec"
    results = []
    for n in counts:
        rate = measure(args, n)
        eff = rate / results[0][1] if results else 1.0
        results.append((n, rate, eff))
        print(f"  {n} worker(s): {rate:.1f} {rate_unit}/worker, "
              f"total {rate * n:.1f}, "
              f"efficiency vs {base}-worker: {eff:.1%}")

    print(json.dumps({
        "metric": f"{args.model}_scaling_efficiency_{base}to"
                  f"{counts[-1]}_workers",
        "value": round(results[-1][2], 4),
        "unit": "fraction",
        "baseline_workers": base,
        "rate_unit": rate_unit,
        "per_worker_rate": {str(n): round(r, 1) for n, r, _ in results},
    }))


if __name__ == "__main__":
    main()
