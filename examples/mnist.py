"""Distributed MNIST training — the end-to-end reference workload.

Capability parity with examples/pytorch_mnist.py in the reference (CS744
fork): argparse surface (--batch-size, --epochs, --lr, momentum, seed,
--batches-per-allreduce), data sharded by worker, DistributedOptimizer, LR
scaled by world size, parameter broadcast at start, checkpoint each epoch on
rank 0 with resume-on-restart (reference :175-195, :305-312), metric
averaging across workers.

Runs on real MNIST if an IDX/npz file is available locally, otherwise on a
synthetic stand-in (this container has no network), which still exercises
every distributed code path.

Usage:
    python examples/mnist.py --epochs 2              # one chip / all chips
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/mnist.py --epochs 2          # 8-worker CPU mesh
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import trainer
from horovod_tpu.models.mnist import MnistCNN
from horovod_tpu.utils import checkpoint


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu MNIST")
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-worker batch size")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.5)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--batches-per-allreduce", type=int, default=1,
                   help="local gradient accumulation before one fused "
                        "allreduce (reference --batches-per-allreduce)")
    p.add_argument("--checkpoint-dir", default="./mnist-ckpt")
    p.add_argument("--data", default=None, help="path to mnist .npz")
    p.add_argument("--steps-per-epoch", type=int, default=None)
    return p.parse_args()


def load_data(path, n=8192):
    if path and os.path.exists(path):
        with np.load(path) as d:
            return (d["x_train"].astype(np.float32)[..., None] / 255.0,
                    d["y_train"].astype(np.int32))
    rng = np.random.RandomState(0)
    X = rng.rand(n, 28, 28, 1).astype(np.float32)
    Y = ((X.mean(axis=(1, 2, 3)) * 1e4) % 10).astype(np.int32)
    return X, Y


def main():
    args = parse_args()
    hvd.init()
    world = hvd.size()
    if hvd.process_rank() == 0:
        print(f"workers={world} devices={jax.devices()[0].platform}")

    X, Y = load_data(args.data)
    global_batch = args.batch_size * world

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    # LR scaled by world size, reference examples/pytorch_mnist.py pattern.
    tx = hvd.DistributedOptimizer(
        optax.sgd(args.lr * world, momentum=args.momentum),
        backward_passes_per_step=args.batches_per_allreduce)
    opt_state = trainer.init_opt_state(tx, params, hvd.mesh())

    start_epoch = 0
    if checkpoint.exists(args.checkpoint_dir):
        (params, opt_state), start_epoch = checkpoint.restore(
            args.checkpoint_dir, like=(params, opt_state))
        print(f"resumed from epoch {start_epoch}")
    # Consistency: all workers start from rank 0's state (reference
    # broadcast_parameters / broadcast_optimizer_state).
    params = hvd.broadcast_parameters(params)
    opt_state = hvd.broadcast_optimizer_state(opt_state)

    def loss_fn(p, batch):
        imgs, labels, dropout_key = batch
        # per-worker dropout mask: fold the worker rank into the step key
        rngs = {"dropout": jax.random.fold_in(dropout_key, hvd.rank())}
        logits = model.apply({"params": p}, imgs, train=True, rngs=rngs)
        return trainer.softmax_cross_entropy(logits, labels)

    axis = hvd.mesh().axis_names[0]
    step = trainer.make_data_parallel_step(
        loss_fn, tx, hvd.mesh(), donate=False,
        batch_specs=(P(axis), P(axis), P()))
    sharding = NamedSharding(hvd.mesh(), P(axis))

    steps_per_epoch = args.steps_per_epoch or max(1, len(X) // global_batch)
    rng = np.random.RandomState(args.seed)
    for epoch in range(start_epoch, args.epochs):
        t0 = time.time()
        perm = rng.permutation(len(X))
        epoch_loss = []
        for i in range(steps_per_epoch):
            idx = perm[(i * global_batch) % len(X):][:global_batch]
            if len(idx) < global_batch:
                idx = np.resize(idx, global_batch)
            imgs = jax.device_put(jnp.asarray(X[idx]), sharding)
            labels = jax.device_put(jnp.asarray(Y[idx]), sharding)
            key = jax.random.PRNGKey(args.seed * 100003 + epoch * 1000 + i)
            params, opt_state, loss = step(params, opt_state,
                                           (imgs, labels, key))
            epoch_loss.append(float(loss))
        # epoch metric averaged across workers (MetricAverageCallback parity)
        avg = float(hvd.allreduce(np.float32(np.mean(epoch_loss))))
        if hvd.process_rank() == 0:
            print(f"epoch {epoch}: loss={avg:.4f} "
                  f"({time.time() - t0:.1f}s, {steps_per_epoch} steps)")
            checkpoint.save(args.checkpoint_dir, (params, opt_state),
                            step=epoch + 1)
    if hvd.process_rank() == 0:
        print("done")


if __name__ == "__main__":
    main()
