"""Distributed MNIST in PyTorch via the torch frontend.

Direct counterpart of the reference's flagship example
(examples/pytorch_mnist.py, including the CS744 fork's checkpoint/resume
additions :175-195, :305-312): torch model and optimizer, hook-driven
gradient allreduce through horovod_tpu's eager core, parameter +
optimizer-state broadcast, --batches-per-allreduce accumulation, per-epoch
rank-0 checkpointing with resume, and metric averaging across workers.

Single process it degrades to ordinary torch training (1-rank Horovod
semantics); multi-process runs via bin/hvdrun launch one torch replica per
process.

Usage:
    python examples/pytorch_mnist.py --epochs 2
    bin/hvdrun -np 2 python examples/pytorch_mnist.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(torch.nn.Module):
    """The reference example's CNN (examples/pytorch_mnist.py:66-84)."""

    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = torch.nn.Conv2d(10, 20, kernel_size=5)
        self.conv2_drop = torch.nn.Dropout2d()
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2_drop(self.conv2(x)), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        x = F.dropout(x, training=self.training)
        return F.log_softmax(self.fc2(x), dim=1)


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu torch MNIST")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.5)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--batches-per-allreduce", type=int, default=1)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--checkpoint-dir", default="./torch-mnist-ckpt")
    p.add_argument("--data", default=None, help="path to mnist .npz")
    p.add_argument("--steps-per-epoch", type=int, default=None)
    return p.parse_args()


def load_data(path, n=8192):
    if path and os.path.exists(path):
        with np.load(path) as d:
            # int64: F.nll_loss requires Long targets
            return (d["x_train"].astype(np.float32)[..., None] / 255.0,
                    d["y_train"].astype(np.int64))
    rng = np.random.RandomState(0)
    X = rng.rand(n, 28, 28, 1).astype(np.float32)
    Y = rng.randint(0, 10, n).astype(np.int64)
    return X, Y


def checkpoint_path(d):
    return os.path.join(d, "checkpoint.pt")


def main():
    args = parse_args()
    hvd.init()
    torch.manual_seed(args.seed)
    world = hvd.size()

    model = Net()
    # LR scaled by world size (reference examples/pytorch_mnist.py pattern)
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(),
                        lr=args.lr * world * args.batches_per_allreduce,
                        momentum=args.momentum),
        named_parameters=model.named_parameters(),
        compression=(hvd.Compression.fp16 if args.fp16_allreduce
                     else hvd.Compression.none),
        backward_passes_per_step=args.batches_per_allreduce)

    start_epoch = 0
    ckpt = checkpoint_path(args.checkpoint_dir)
    if os.path.exists(ckpt) and hvd.rank() == 0:
        state = torch.load(ckpt, weights_only=True)
        model.load_state_dict(state["model"])
        optimizer.load_state_dict(state["optimizer"])
        start_epoch = state["epoch"] + 1
    # everyone adopts rank 0's weights/state/epoch — the reference's
    # resume consistency primitive (torch/__init__.py:200-348)
    start_epoch = hvd.broadcast_object(start_epoch, root_rank=0)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    if start_epoch and hvd.rank() == 0:
        print(f"resumed from epoch {start_epoch}")

    X, Y = load_data(args.data)
    # steps from the GLOBAL length before sharding: per-shard lengths can
    # differ by one, and a rank running an extra step would enqueue
    # allreduces no peer matches (DistributedSampler's padding solves the
    # same problem in the reference)
    steps = args.steps_per_epoch or max(1, (len(X) // world)
                                        // args.batch_size)
    # shard the dataset by rank (DistributedSampler role)
    X, Y = X[hvd.rank()::world], Y[hvd.rank()::world]
    X = torch.from_numpy(np.ascontiguousarray(X.transpose(0, 3, 1, 2)))
    Y = torch.from_numpy(Y)
    model.train()
    for epoch in range(start_epoch, args.epochs):
        perm = torch.randperm(len(X))
        epoch_loss = []
        for i in range(steps):
            optimizer.zero_grad()
            for k in range(args.batches_per_allreduce):
                idx = perm[((i * args.batches_per_allreduce + k)
                            * args.batch_size) % len(X):][:args.batch_size]
                loss = F.nll_loss(model(X[idx]), Y[idx])
                (loss / args.batches_per_allreduce).backward()
            optimizer.step()
            epoch_loss.append(loss.item())
        # epoch metric averaged across workers (MetricAverageCallback role)
        avg = hvd.allreduce(torch.tensor(float(np.mean(epoch_loss))),
                            average=True).item()
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={avg:.4f}")
            os.makedirs(args.checkpoint_dir, exist_ok=True)
            torch.save({"model": model.state_dict(),
                        "optimizer": optimizer.state_dict(),
                        "epoch": epoch}, ckpt)


if __name__ == "__main__":
    main()
