"""Eager allreduce throughput microbenchmark: measures the tensor-fusion
win directly (bytes/µs with fusion on vs HOROVOD_FUSION_THRESHOLD=0), the
same score the autotuner optimizes (reference ParameterManager,
parameter_manager.cc:155-210) and the measurable knob SURVEY's design
translation calls for.

Enqueues N same-sized tensors async (the gradient-burst pattern a backward
pass produces), flushes once, joins — fused: few bucketed collectives;
unfused: one collective per tensor.

    python examples/allreduce_benchmark.py
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/allreduce_benchmark.py --sizes-kb 4,64,1024
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import horovod_tpu as hvd


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--num-tensors", type=int, default=32,
                   help="tensors per burst (one backward pass's gradients)")
    p.add_argument("--sizes-kb", default="4,64,1024",
                   help="per-tensor payload sizes to sweep, KB")
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--num-proc", type=int, default=1,
                   help=">1: spawn processes and measure the negotiated "
                        "multi-process path (rank-0 coordinator fusion) "
                        "instead of the single-controller stacked path")
    return p.parse_args()


def measure(n_tensors, elems, iters):
    """Mean bytes/µs for a burst of n_tensors stacked [world, elems]
    float32 allreduces (timed after one untimed warmup burst)."""
    import horovod_tpu.common.state as state
    world = hvd.size()
    coord = state.global_state().coordinator
    tensors = [np.full((world, elems), float(i), np.float32)
               for i in range(n_tensors)]
    nbytes = sum(t.nbytes for t in tensors)
    rates = []
    for it in range(iters + 1):
        with coord.hold_cycle():  # the burst lands in one fused cycle
            handles = [hvd.allreduce_async(t, average=False,
                                           name=f"ar.{it}.{i}")
                       for i, t in enumerate(tensors)]
        t0 = time.perf_counter()
        coord.flush()
        outs = [hvd.synchronize(h) for h in handles]
        for o in outs:
            np.asarray(o)  # device-to-host read: the completion barrier
        dt = time.perf_counter() - t0
        if it > 0:  # first burst warms compilation caches
            rates.append(nbytes / dt / 1e6)  # bytes/µs
    return float(np.mean(rates))


def _measure_multiproc(num_proc, n_tensors, sizes_kb, iters, threshold):
    """Per-size bytes/µs for bursts of replicated allreduces across
    num_proc real processes: with the default threshold the rank-0
    negotiation coordinator fuses each burst into few cross-process
    collectives; with HOROVOD_FUSION_THRESHOLD=0 every tensor pays its
    own round. One launch sweeps every size — process spawn + rendezvous
    + backend import are paid once per threshold, not per point."""
    from horovod_tpu.run.launch import run

    def fn(n_tensors, sizes_kb, iters):
        import time as _time
        import numpy as _np
        import horovod_tpu as _hvd
        _hvd.init()
        out = {}
        for kb in sizes_kb:
            elems = max(1, kb * 1024 // 4)
            tensors = [_np.full((elems,), float(i), _np.float32)
                       for i in range(n_tensors)]
            nbytes = sum(t.nbytes for t in tensors)
            rates = []
            for it in range(iters + 1):
                t0 = _time.perf_counter()
                handles = [_hvd.allreduce_async(
                    t, average=False, name=f"ar.{kb}.{it}.{i}")
                    for i, t in enumerate(tensors)]
                for h in handles:
                    _np.asarray(_hvd.synchronize(h))
                dt = _time.perf_counter() - t0
                if it > 0:
                    rates.append(nbytes / dt / 1e6)
            out[kb] = sum(rates) / len(rates)
        _hvd.shutdown()
        return out

    env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "HOROVOD_FUSION_THRESHOLD": str(threshold)}
    per_rank = run(fn, args=(n_tensors, sizes_kb, iters),
                   num_proc=num_proc, env=env)
    return {kb: float(np.mean([r[kb] for r in per_rank]))
            for kb in sizes_kb}


def main():
    args = parse_args()
    if args.iters < 1:
        raise SystemExit("--iters must be >= 1")
    if args.num_proc > 1:
        sizes_kb = [int(s) for s in args.sizes_kb.split(",")]
        fused = _measure_multiproc(args.num_proc, args.num_tensors,
                                   sizes_kb, args.iters, 64 << 20)
        unfused = _measure_multiproc(args.num_proc, args.num_tensors,
                                     sizes_kb, args.iters, 0)
        results = {}
        for kb in sizes_kb:
            results[f"{kb}KB"] = {
                "fused_bytes_per_us": round(fused[kb], 3),
                "unfused_bytes_per_us": round(unfused[kb], 3),
                "speedup": round(fused[kb] / unfused[kb], 2)}
            print(f"{args.num_proc} proc, {args.num_tensors} x {kb} KB: "
                  f"negotiated-fused {fused[kb]:.2f} B/us, unfused "
                  f"{unfused[kb]:.2f} B/us, {fused[kb] / unfused[kb]:.2f}x")
        print(json.dumps({
            "metric": "negotiated_allreduce_fusion_speedup",
            "num_proc": args.num_proc,
            "num_tensors": args.num_tensors, "results": results}))
        return
    hvd.init()
    from horovod_tpu.common import state
    sizes_kb = [int(s) for s in args.sizes_kb.split(",")]
    results = {}
    for kb in sizes_kb:
        elems = max(1, kb * 1024 // 4 // hvd.size())
        fused = measure(args.num_tensors, elems, args.iters)
        cfg = state.global_state().config
        saved = cfg.fusion_threshold
        cfg.fusion_threshold = 0  # one collective per tensor
        try:
            unfused = measure(args.num_tensors, elems, args.iters)
        finally:
            cfg.fusion_threshold = saved
        results[f"{kb}KB"] = {"fused_bytes_per_us": round(fused, 3),
                              "unfused_bytes_per_us": round(unfused, 3),
                              "speedup": round(fused / unfused, 2)}
        print(f"{args.num_tensors} x {kb} KB: fused {fused:.2f} B/us, "
              f"unfused {unfused:.2f} B/us, "
              f"{fused / unfused:.2f}x")
    print(json.dumps({"metric": "eager_allreduce_fusion_speedup",
                      "num_tensors": args.num_tensors,
                      "results": results}))


if __name__ == "__main__":
    main()
