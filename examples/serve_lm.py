"""Serve the transformer LM with continuous batching (docs/serving.md).

Generates synthetic open-loop Poisson traffic against the serving
engine (horovod_tpu/serving/) and reports decode throughput plus
per-request SLO latencies — and, with ``--baseline``, runs the SAME
engine in drain (static-batch) mode so the two scheduling policies are
compared at an equal slot budget. bench.py's HVD_BENCH_SERVE leg
imports this module's harness functions; running it standalone prints
one JSON result line.

Usage:
    # CPU, tiny config, continuous vs static side by side
    JAX_PLATFORMS=cpu python examples/serve_lm.py --baseline

    # heavier load, more slots
    python examples/serve_lm.py --slots 8 --requests 96 --rate 0.8
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import transformer as tr
from horovod_tpu.serving.engine import ServeEngine
from horovod_tpu.serving.queue import AdmissionQueue, Request
from horovod_tpu.utils import metrics as hvd_metrics


def serving_config(on_tpu):
    """The LM this example serves: the flagship config on TPU, the tiny
    fp32 config on CPU (fp32 because CPU bf16 emulation is slow and the
    example's point is scheduling, not dtype)."""
    if on_tpu:
        return tr.TransformerConfig.gpt2_small_tpu(
            attention_impl="flash")
    return tr.TransformerConfig.tiny(dtype=jnp.float32,
                                     attention_impl="full")


def make_workload(seed, n_requests, rate, short_tokens=8, long_tokens=40,
                  long_frac=0.25, prompt_lens=(4, 8), temperature=0.0):
    """Open-loop Poisson arrival schedule: [(arrival_step, Request)].

    Arrival times are exponential inter-arrival gaps at ``rate``
    requests per decode step — open-loop, so the schedule never adapts
    to how the engine is doing (the honest way to measure overload).
    Decode lengths are bimodal (mostly short, a heavy tail of long)
    because that is the regime where continuous batching pays: under
    drain scheduling every short request in a wave waits for the wave's
    longest.
    """
    r = np.random.RandomState(seed)
    t = 0.0
    workload = []
    for i in range(n_requests):
        t += r.exponential(1.0 / rate)
        n_new = long_tokens if r.rand() < long_frac else short_tokens
        plen = int(r.randint(prompt_lens[0], prompt_lens[1] + 1))
        prompt = tuple(int(x) for x in r.randint(1, 250, plen))
        workload.append((t, Request(f"req-{i}", prompt,
                                    max_new_tokens=n_new,
                                    temperature=temperature)))
    return workload


def run_load(engine, workload, max_steps=100000):
    """Drive the engine under the arrival schedule: submit every request
    whose arrival step has passed, then step. Returns (results,
    decode_steps, wall_s)."""
    i = 0
    steps = 0
    results = []
    t0 = time.monotonic()
    while i < len(workload) or engine.active_count or len(engine.queue):
        while i < len(workload) and workload[i][0] <= steps:
            engine.submit(workload[i][1])
            i += 1
        results.extend(engine.step())
        steps += 1
        if steps >= max_steps:
            raise RuntimeError(
                f"load never drained in {max_steps} steps "
                f"({len(results)} done, {engine.active_count} active)")
    return results, steps, time.monotonic() - t0


def serve_workload(cfg, params, workload, policy, num_slots, max_len,
                   kv_block=8, seed=0):
    """One arm of the comparison: serve ``workload`` under ``policy``
    and summarize throughput + latency. Fresh engine per arm so the
    arms share nothing but params."""
    queue = AdmissionQueue(max_depth=len(workload) + 1,
                           admission_timeout_s=1e9)
    engine = ServeEngine(cfg, params, num_slots=num_slots,
                         max_len=max_len, kv_block=kv_block,
                         policy=policy, queue=queue, seed=seed)
    results, steps, wall_s = run_load(engine, workload)
    completed = [r for r in results if r.outcome == "completed"]
    decode_tokens = sum(len(r.tokens) for r in completed)
    ttfts = sorted(r.ttft_s for r in completed if r.ttft_s is not None)

    def pct(q):
        if not ttfts:
            return None
        return ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))]
    assert engine.kv.ledger.blocks_in_use == 0, "KV blocks leaked"
    return {
        "policy": policy,
        "completed": len(completed),
        "failed": len(results) - len(completed),
        "decode_tokens": decode_tokens,
        "steps": steps,
        "tokens_per_step": decode_tokens / max(steps, 1),
        "wall_s": round(wall_s, 3),
        "tokens_per_s": round(decode_tokens / wall_s, 1) if wall_s else 0,
        "ttft_p50_s": pct(0.50),
        "ttft_p99_s": pct(0.99),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--rate", type=float, default=0.5,
                    help="mean arrivals per decode step (open loop)")
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--kv-block", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--baseline", action="store_true",
                    help="also run the drain (static-batch) arm and "
                         "report the speedup")
    args = ap.parse_args(argv)

    on_tpu = jax.default_backend() == "tpu"
    cfg = serving_config(on_tpu)
    _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
    workload = make_workload(args.seed, args.requests, args.rate,
                             temperature=args.temperature)

    out = {"backend": jax.default_backend(), "slots": args.slots,
           "requests": args.requests, "rate": args.rate}
    out["continuous"] = serve_workload(
        cfg, params, workload, "continuous", args.slots, args.max_len,
        kv_block=args.kv_block, seed=args.seed)
    if args.baseline:
        out["static"] = serve_workload(
            cfg, params, workload, "drain", args.slots, args.max_len,
            kv_block=args.kv_block, seed=args.seed)
        out["speedup_tokens_per_step"] = round(
            out["continuous"]["tokens_per_step"] /
            max(out["static"]["tokens_per_step"], 1e-9), 3)
    out["metrics"] = hvd_metrics.get_registry().snapshot(max_events=8)
    print(json.dumps(out, default=float))
    return 0


if __name__ == "__main__":
    sys.exit(main())
