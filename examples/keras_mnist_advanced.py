"""Distributed Keras MNIST, advanced recipe — reference
examples/keras_mnist_advanced.py parity on Keras 3:

  * size-scaled LR with ``LearningRateWarmupCallback`` ramping it in over
    the first epochs (arXiv:1706.02677) — per-batch, with momentum
    correction through the compiled train step
  * ``LearningRateScheduleCallback`` piecewise decay after the warmup
  * ``MetricAverageCallback`` BEFORE ``ReduceLROnPlateau``, so the
    plateau detector sees the all-worker metric, not one shard's
  * validation with 3/N over-sampling per worker (the reference's trick
    to raise the chance every validation example is seen by someone)
  * in-model augmentation (RandomRotation/Translation/Zoom preprocessing
    layers — the Keras 3 replacement for ImageDataGenerator)
  * rank-0-only checkpointing

Runs on the TF backend by default, or on the JAX backend with
KERAS_BACKEND=jax.

Usage:
    python examples/keras_mnist_advanced.py --epochs 6
    bin/hvdrun -np 2 python examples/keras_mnist_advanced.py --epochs 6
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import horovod_tpu.keras as hvd


def parse_args():
    p = argparse.ArgumentParser(
        description="horovod_tpu keras MNIST (advanced: warmup + "
                    "schedule + plateau callbacks)")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--warmup-epochs", type=int, default=2)
    p.add_argument("--decay-epoch", type=int, default=4,
                   help="epoch at which the 10x LR decay kicks in")
    p.add_argument("--checkpoint-dir", default="./keras-mnist-adv-ckpt")
    p.add_argument("--data", default=None, help="path to mnist .npz")
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--val-steps", type=int, default=None)
    return p.parse_args()


def load_data(path, n=8192, n_val=2048):
    if path and os.path.exists(path):
        with np.load(path) as d:
            return ((d["x_train"].astype(np.float32)[..., None] / 255.0,
                     d["y_train"].astype(np.int64)),
                    (d["x_test"].astype(np.float32)[..., None] / 255.0,
                     d["y_test"].astype(np.int64)))
    rng = np.random.RandomState(0)
    return ((rng.rand(n, 28, 28, 1).astype(np.float32),
             rng.randint(0, 10, n).astype(np.int64)),
            (rng.rand(n_val, 28, 28, 1).astype(np.float32),
             rng.randint(0, 10, n_val).astype(np.int64)))


def build_model():
    import keras

    return keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        # augmentation lives in the model (active only during fit) —
        # the Keras 3 stand-in for the reference's ImageDataGenerator
        keras.layers.RandomRotation(0.02),
        keras.layers.RandomTranslation(0.08, 0.08),
        keras.layers.RandomZoom(0.08),
        keras.layers.Conv2D(32, 3, activation="relu"),
        keras.layers.Conv2D(64, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Dropout(0.25),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(10, activation="softmax")])


def main():
    args = parse_args()
    hvd.init()
    import keras

    world = hvd.size()
    model = build_model()
    # size-scaled LR; the warmup callback ramps up to it from lr/size
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            keras.optimizers.SGD(args.lr * world,
                                 momentum=args.momentum)),
        loss="sparse_categorical_crossentropy", metrics=["accuracy"],
        jit_compile=False)

    (X, Y), (Xv, Yv) = load_data(args.data)
    steps = args.steps_per_epoch or max(1, (len(X) // world)
                                        // args.batch_size)
    X, Y = X[hvd.rank()::world], Y[hvd.rank()::world]
    # 3/N over-sampled validation: each worker takes a DIFFERENT rotated
    # window of ~3/N of the validation set (capped at the full set), so
    # the shards overlap 3x and together cover every example — the
    # reference's random-sampling trick, deterministic here
    take = min(len(Xv), max(args.batch_size,
                            3 * len(Xv) // world))
    start = hvd.rank() * (len(Xv) // world)
    idx = (np.arange(take) + start) % len(Xv)
    Xv, Yv = Xv[idx], Yv[idx]
    val_steps = args.val_steps or max(1, take // args.batch_size)

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        # must precede ReduceLROnPlateau: the plateau detector reads the
        # all-worker averaged metric this writes back into logs
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=args.warmup_epochs, steps_per_epoch=steps,
            verbose=1 if hvd.rank() == 0 else 0),
        # one-shot 10x decay at the decay epoch (end_epoch bounds it:
        # re-asserting initial_lr*0.1 every later epoch would silently
        # undo any reduction ReduceLROnPlateau makes below)
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=0.1, start_epoch=args.decay_epoch,
            end_epoch=args.decay_epoch + 1),
        keras.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                          patience=2,
                                          verbose=1 if hvd.rank() == 0
                                          else 0),
    ]
    if hvd.rank() == 0:
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            os.path.join(args.checkpoint_dir, "checkpoint.keras")))

    model.fit(X, Y, batch_size=args.batch_size, epochs=args.epochs,
              steps_per_epoch=steps,
              validation_data=(Xv, Yv), validation_steps=val_steps,
              validation_batch_size=args.batch_size,
              callbacks=callbacks,
              verbose=1 if hvd.rank() == 0 else 0)

    score = model.evaluate(Xv, Yv, batch_size=args.batch_size, verbose=0)
    if hvd.rank() == 0:
        final_lr = float(np.asarray(model.optimizer.learning_rate))
        print(f"Test loss: {score[0]:.4f}")
        print(f"Test accuracy: {score[1]:.4f}")
        print(f"Final lr: {final_lr:.6f} (initial {args.lr * world:.4f})")


if __name__ == "__main__":
    main()
