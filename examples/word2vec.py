"""Distributed skip-gram word2vec — the sparse-gradient workload.

Capability parity with the reference's examples/tensorflow_word2vec.py:
skip-gram pairs with negative sampling, an embedding matrix whose gradients
touch only the rows in the batch, LR scaled by world size, and — the point
of the example — **sparse gradient allreduce**: instead of densely summing a
[vocab, dim] gradient, each worker's touched rows are allgathered as
IndexedSlices (values + indices) and scatter-added, the reference's
IndexedSlices→allgather path (reference tensorflow/__init__.py:62-73).

The corpus is synthetic Zipf-distributed token text (the reference downloads
text8; this container has no network); the distributed mechanics are
identical. At the end the nearest neighbours of a few frequent tokens are
printed (cosine similarity), as the reference does.

Usage:
    python examples/word2vec.py --steps 200
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/word2vec.py --steps 100
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.sparse import (IndexedSlices, grouped_sparse_allreduce,
                                    sparse_allreduce)


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu word2vec")
    p.add_argument("--vocab-size", type=int, default=5000)
    p.add_argument("--embedding-dim", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=128,
                   help="per-worker skip-gram pairs per step")
    p.add_argument("--num-negatives", type=int, default=8)
    p.add_argument("--window", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--steps", type=int, default=500)
    p.add_argument("--corpus-len", type=int, default=200_000)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--eager", action="store_true",
                   help="multi-process eager mode: sparse gradients ride "
                        "grouped_sparse_allreduce, whose allgathers the "
                        "negotiated coordinator fuses into single "
                        "allgatherv collectives (launch under bin/hvdrun)")
    return p.parse_args()


def make_corpus(vocab, n, seed):
    """Zipf-ish token stream with local correlations (so neighbours are
    learnable): tokens come in correlated runs."""
    rng = np.random.RandomState(seed)
    base = rng.zipf(1.3, n).astype(np.int64) % vocab
    # correlate: every even position tends to be followed by token+1
    nxt = np.roll(base, -1)
    mask = rng.rand(n) < 0.5
    nxt[mask] = (base[mask] + 1) % vocab
    out = np.empty(n, np.int32)
    out[0::2] = base[0::2]
    out[1::2] = nxt[0::2][: len(out[1::2])]
    return out


def skipgram_batches(corpus, window, batch, rng):
    centers = rng.randint(window, len(corpus) - window, batch)
    offs = rng.randint(1, window + 1, batch) * rng.choice([-1, 1], batch)
    return corpus[centers], corpus[centers + offs]


def main():
    args = parse_args()
    hvd.init()
    world = hvd.size()
    axis = hvd.mesh().axis_names[0]
    verbose = hvd.process_rank() == 0
    if verbose:
        print(f"workers={world} vocab={args.vocab_size} "
              f"dim={args.embedding_dim}")

    rng = np.random.RandomState(args.seed)
    corpus = make_corpus(args.vocab_size, args.corpus_len, args.seed)

    key = jax.random.PRNGKey(args.seed)
    emb = jax.random.uniform(key, (args.vocab_size, args.embedding_dim),
                             jnp.float32, -0.5, 0.5)
    ctx = jnp.zeros((args.vocab_size, args.embedding_dim), jnp.float32)
    emb = hvd.broadcast_parameters(emb)

    B, K = args.batch_size, args.num_negatives
    lr = args.lr * world  # reference scales LR by hvd.size()

    def loss_fn(c_rows, pos_rows, neg_rows):
        pos_logit = jnp.sum(c_rows * pos_rows, -1)            # [B]
        neg_logit = jnp.einsum("bd,bkd->bk", c_rows, neg_rows)
        return (-jnp.mean(jax.nn.log_sigmoid(pos_logit))
                - jnp.mean(jnp.sum(jax.nn.log_sigmoid(-neg_logit), -1)))

    if args.eager:
        # Per-process eager training: local grads, then ONE grouped
        # sparse allreduce per step — the coordinator fuses its six
        # allgathers (3 float values + 3 int32 indices) into two
        # allgatherv collectives, and after step 1 every announcement is
        # a response-cache bit.
        nproc = hvd.process_count()
        lr = args.lr * nproc
        grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))
        proc_rng = np.random.RandomState(args.seed + hvd.process_rank())
        t0 = time.time()
        avg = None
        for i in range(args.steps):
            centers, contexts = skipgram_batches(corpus, args.window, B,
                                                 proc_rng)
            negs = proc_rng.randint(0, args.vocab_size, (B, K))
            centers = jnp.asarray(centers)
            contexts = jnp.asarray(contexts)
            negs_j = jnp.asarray(negs)
            loss, (g_c, g_pos, g_neg) = grad_fn(
                emb[centers], ctx[contexts], ctx[negs_j])
            g_emb, g_ctx_pos, g_ctx_neg = grouped_sparse_allreduce(
                [IndexedSlices(g_c, centers, emb.shape),
                 IndexedSlices(g_pos, contexts, ctx.shape),
                 IndexedSlices(g_neg.reshape(B * K, -1),
                               negs_j.reshape(B * K), ctx.shape)],
                average=True, name="w2v")  # stable names → cache hits
            emb = emb.at[g_emb.indices].add(-lr * g_emb.values)
            ctx = ctx.at[g_ctx_pos.indices].add(-lr * g_ctx_pos.values)
            ctx = ctx.at[g_ctx_neg.indices].add(-lr * g_ctx_neg.values)
            loss = float(np.asarray(hvd.allreduce(
                np.asarray(loss, np.float32), average=True)))
            avg = loss if avg is None else 0.95 * avg + 0.05 * loss
            if verbose and (i + 1) % max(1, args.steps // 10) == 0:
                print(f"step {i + 1}: loss={avg:.4f}")
        if verbose:
            print(f"[eager x{nproc} procs] {args.steps} steps in "
                  f"{time.time() - t0:.1f}s  final loss={avg:.4f}")
        hvd.shutdown()
        return

    def step(emb, ctx, center, context, negs):
        """One negative-sampling step on this worker's pairs; gradients are
        sparse rows, allreduced via the IndexedSlices allgather path."""
        c_rows = emb[center]                      # [B, D]
        pos_rows = ctx[context]                   # [B, D]
        neg_rows = ctx[negs]                      # [B, K, D]

        loss, (g_c, g_pos, g_neg) = jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2))(c_rows, pos_rows, neg_rows)

        # row-gradients → IndexedSlices → allgather-style allreduce: each
        # worker applies the union of every worker's touched rows.
        g_emb = sparse_allreduce(
            IndexedSlices(g_c, center, emb.shape), average=True,
            axis_name=axis)
        g_ctx_pos = sparse_allreduce(
            IndexedSlices(g_pos, context, ctx.shape), average=True,
            axis_name=axis)
        g_ctx_neg = sparse_allreduce(
            IndexedSlices(g_neg.reshape(B * K, -1), negs.reshape(B * K),
                          ctx.shape), average=True, axis_name=axis)

        emb = emb.at[g_emb.indices].add(-lr * g_emb.values)
        ctx = ctx.at[g_ctx_pos.indices].add(-lr * g_ctx_pos.values)
        ctx = ctx.at[g_ctx_neg.indices].add(-lr * g_ctx_neg.values)
        return emb, ctx, jax.lax.pmean(loss, axis)

    mesh = hvd.mesh()
    # check_vma=False: the embedding updates are built from allgathered
    # (hence replicated) rows, which shard_map's replication checker can't
    # infer through the scatter-add.
    jstep = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P()), check_vma=False))
    shard = NamedSharding(mesh, P(axis))

    t0 = time.time()
    avg = None
    for i in range(args.steps):
        centers, contexts = skipgram_batches(corpus, args.window,
                                             B * world, rng)
        negs = rng.randint(0, args.vocab_size, (B * world, K))
        emb, ctx, loss = jstep(
            emb, ctx,
            jax.device_put(jnp.asarray(centers), shard),
            jax.device_put(jnp.asarray(contexts), shard),
            jax.device_put(jnp.asarray(negs), shard))
        avg = float(loss) if avg is None else 0.95 * avg + 0.05 * float(loss)
        if verbose and (i + 1) % max(1, args.steps // 10) == 0:
            print(f"step {i + 1}: loss={avg:.4f}")
    if verbose:
        print(f"{args.steps} steps in {time.time() - t0:.1f}s")

        # nearest neighbours of a few tokens by cosine similarity
        # (reference prints 'Nearest to <word>: ...')
        e = np.asarray(emb)
        e = e / (np.linalg.norm(e, axis=1, keepdims=True) + 1e-8)
        for tok in [1, 2, 3, 5, 8]:
            sims = e @ e[tok]
            nearest = [int(t) for t in np.argsort(-sims)[1:6]]
            print(f"Nearest to {tok}: {nearest}")


if __name__ == "__main__":
    main()
