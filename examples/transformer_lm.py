"""Flagship transformer-LM training across the full mesh (dp x tp x sp).

The workload the reference never had but its successors need: a GPT-style
decoder trained with every parallelism axis this framework provides —
data parallel (gradient psum, the reference's core capability), tensor
parallel (Megatron-style sharded heads/MLP), and sequence parallel
(ring/Ulysses attention for long context). One script, one mesh, `pjit`
does the rest.

Usage:
    # single chip / all local chips, GPT-2-small-ish, synthetic tokens
    python examples/transformer_lm.py --steps 20

    # 8-way CPU mesh: 2-way dp x 2-way tp x 2-way sp with ring attention
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transformer_lm.py --dp 2 --tp 2 --sp 2 \
        --attention ring --size tiny --steps 5

    # throughput benchmark mode (tokens/sec, docs/benchmarks.md)
    python examples/transformer_lm.py --bench --steps 30
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import trainer
from horovod_tpu.common.exceptions import PREEMPTED_EXIT_CODE
from horovod_tpu.models import transformer as tr
from horovod_tpu.parallel import mesh as mesh_mod


SIZES = {"tiny": tr.TransformerConfig.tiny,
         "gpt2-small": tr.TransformerConfig.gpt2_small,
         "gpt2-small-tpu": tr.TransformerConfig.gpt2_small_tpu,
         "llama-1b": tr.TransformerConfig.llama_1b}


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu transformer LM")
    p.add_argument("--size", default="tiny", choices=sorted(SIZES))
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel ways (default: all devices / tp / sp)")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel ways")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel ways (ring/ulysses attention)")
    p.add_argument("--attention", default="full",
                   choices=["full", "ring", "ring_flash", "ulysses",
                            "flash"])
    p.add_argument("--batch-size", type=int, default=4,
                   help="per-dp-way batch size")
    p.add_argument("--seq-len", type=int, default=None)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=10)
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel ways (MoE experts shard over 'ep')")
    p.add_argument("--num-experts", type=int, default=0,
                   help="experts per MoE layer; 0 = dense MLP")
    p.add_argument("--remat-policy", default=None,
                   choices=["dots", "dots_no_batch"],
                   help="jax.checkpoint policy under --remat (default: "
                        "save nothing)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each block (HBM for FLOPs)")
    p.add_argument("--vocab-chunk", type=int, default=0,
                   help="compute the loss blockwise over this many vocab "
                        "entries instead of materializing [B,S,V] logits "
                        "(memory-bound large-batch/long-seq configs)")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=100,
                   help="save an async checkpoint every N steps "
                        "(trainer.Checkpointer contract: auto-resume on "
                        "start, SIGTERM/SIGINT exits preemption-safe "
                        "with an emergency save and code 45)")
    p.add_argument("--eager-allreduce", action="store_true",
                   help="average gradients through the EAGER collective "
                        "core (fused stacked allreduce per step) instead "
                        "of the in-graph GSPMD psum — the regime "
                        "HOROVOD_AUTOTUNE's passive scorer observes, so "
                        "autotuning tunes against these exact steps. "
                        "Pure data-parallel only (tp/sp/ep must be 1).")
    p.add_argument("--bench", action="store_true",
                   help="skip checkpointing/logging; print tokens/sec")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    n = hvd.size()
    # The named-mesh data plane (docs/mesh.md): CLI flags win when given;
    # otherwise the HOROVOD_MESH / HOROVOD_MESH_TP / HOROVOD_MESH_SP env
    # knobs configure the layout, and with nothing set this is the same
    # pure-dp mesh as always. The result is committed as THE process
    # mesh — trainer/checkpoint/serving helpers all place through it.
    cli = (args.dp is not None or args.tp != 1 or args.sp != 1 or
           args.ep != 1)
    if cli:
        dp = args.dp or n // (args.tp * args.sp * args.ep)
        if dp * args.tp * args.sp * args.ep != n:
            raise SystemExit(
                f"dp*tp*sp*ep = {dp}*{args.tp}*{args.sp}*{args.ep} "
                f"!= {n} devices")
        mesh = mesh_mod.build_mesh(dp=dp, tp=args.tp, sp=args.sp,
                                   ep=args.ep)
    else:
        mesh = mesh_mod.mesh_from_env()
    mesh_mod.set_global_mesh(mesh)
    dp = mesh_mod.mesh_axis_size(mesh, "dp")
    tp = mesh_mod.mesh_axis_size(mesh, "tp")
    sp = mesh_mod.mesh_axis_size(mesh, "sp")
    ep = mesh_mod.mesh_axis_size(mesh, "ep")
    verbose = hvd.process_rank() == 0

    cfg = SIZES[args.size](attention_impl=args.attention, remat=args.remat,
                           remat_policy=args.remat_policy,
                           num_experts=args.num_experts)
    seq = args.seq_len or min(cfg.max_seq_len, 256)
    batch = args.batch_size * dp
    if verbose:
        print(f"mesh dp={dp} tp={tp} sp={sp} "
              f"model={args.size} seq={seq} attention={args.attention}")

    model = tr.TransformerLM(cfg)
    rng = np.random.RandomState(args.seed)
    sample = jnp.zeros((2, seq), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), sample)["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    if verbose:
        print(f"{n_params / 1e6:.1f}M params")

    # LR: linear warmup then cosine — the jit-friendly schedule form of the
    # reference's LearningRateWarmupCallback (callbacks.warmup_schedule is
    # the epoch-keyed equivalent).
    sched = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, args.warmup_steps, max(args.steps, 2 * args.warmup_steps))
    tx = optax.adamw(sched, weight_decay=0.01)

    specs = None
    if args.eager_allreduce:
        if tp * sp * ep != 1:
            raise SystemExit("--eager-allreduce is pure data-parallel: "
                             "tp/sp/ep must all be 1")
        from bench_common import build_eager_lm_step
        step, params, opt_state, _ = build_eager_lm_step(
            cfg, n, args.batch_size, seq, tx=tx, params=params)
        if verbose:
            print("eager allreduce: gradients ride the coordination core "
                  "(autotune-scorable; HOROVOD_AUTOTUNE=1 to tune)")
    else:
        loss_fn = tr.lm_loss_fn(model, vocab_chunk=args.vocab_chunk)
        specs = tr.param_specs(params)
        step, param_shardings, batch_sharding = trainer.make_gspmd_step(
            loss_fn, tx, mesh, specs, tr.batch_spec(sp=sp > 1),
            params=params)
        # tree-wide placement through the sanctioned helper (HVD019):
        # one batched transfer, every leaf pinned by its spec
        params = trainer.place(params, mesh, specs)
        opt_state = trainer.init_opt_state(tx, params, mesh, specs)

    # Checkpoint plane (docs/checkpoint.md): async saves every
    # --checkpoint-every steps, auto-resume, preemption-safe SIGTERM
    # exit. Only when every leaf is host-addressable — multi-host
    # sharded params need a gather or per-process checkpointing.
    addressable = all(getattr(x, "is_fully_addressable", True)
                      for x in jax.tree_util.tree_leaves(
                          (params, opt_state)))
    ckptr = None
    start_step = 0
    if args.checkpoint_dir and not args.eager_allreduce and not args.bench:
        if addressable:
            ckptr = trainer.Checkpointer(
                args.checkpoint_dir, every=args.checkpoint_every,
                preemption=jax.process_index() == 0,
                rank=jax.process_index(), verbose=verbose,
                layout=mesh_mod.mesh_layout(mesh))
            # cross-layout resume (docs/mesh.md): the checkpoint may have
            # been saved under a different dp×tp×sp factorization — the
            # spec tree re-places every leaf on THIS run's mesh
            resume_specs = (specs,
                            trainer.opt_state_specs(tx, params, specs))
            (params, opt_state), start_step, _extra = ckptr.resume(
                like=(params, opt_state), mesh=mesh,
                spec_tree=resume_specs)
        elif verbose:
            print("checkpointing disabled: params span non-addressable "
                  "devices (multi-host sharded); gather or use "
                  "per-process checkpointing")

    def batch_tokens():
        # [batch, seq]; the loss shifts inputs/targets internally. seq (not
        # seq+1) keeps the sequence dim divisible by sp for device_put.
        if args.eager_allreduce:
            # stacked eager layout: [world, per_shard, seq]
            toks = rng.randint(0, cfg.vocab_size,
                               (n, args.batch_size, seq),
                               dtype=np.int64).astype(np.int32)
            return jnp.asarray(toks)
        toks = rng.randint(0, cfg.vocab_size, (batch, seq),
                           dtype=np.int64).astype(np.int32)
        return jax.device_put(jnp.asarray(toks), batch_sharding)

    # compile + warmup (scalar read = true barrier, see timing note below)
    params, opt_state, loss = step(params, opt_state, batch_tokens())
    float(loss)

    # Per-axis wire attribution (docs/metrics.md): analytic payload bytes
    # of the step's collectives, split by mesh axis — the dp leg is the
    # gradient allreduce (every param), the tp leg the Megatron
    # activation allreduces (2 fwd + 2 bwd per layer of one dp-shard's
    # [B/dp, S, D] residual). GSPMD hides the executed collectives inside
    # the compiled step, so the counters carry the model, not a probe.
    itemsize = jnp.dtype(cfg.dtype).itemsize
    dp_step_bytes = sum(x.size * np.dtype(x.dtype).itemsize
                        for x in jax.tree_util.tree_leaves(params)) \
        if dp > 1 else 0
    tp_step_bytes = (4 * cfg.num_layers * (batch // dp) * seq *
                     cfg.d_model * itemsize) if tp > 1 else 0

    t0 = time.perf_counter()
    tokens_done = 0
    for i in range(start_step, args.steps):
        params, opt_state, loss = step(params, opt_state, batch_tokens())
        tokens_done += batch * seq
        if dp_step_bytes:
            mesh_mod.account_axis_bytes("dp", dp_step_bytes)
        if tp_step_bytes:
            mesh_mod.account_axis_bytes("tp", tp_step_bytes)
        if not args.bench and verbose and (i + 1) % 10 == 0:
            print(f"step {i + 1}: loss={float(loss):.4f}")
        if ckptr is not None and ckptr.step_end(
                i + 1, (params, opt_state), extra={"data_pos": i + 1}):
            # preemption: the in-flight step finished, an emergency
            # checkpoint committed; the elastic supervisor's
            # --graceful-restart-on-preempt resumes from exactly here
            sys.exit(PREEMPTED_EXIT_CODE)
    # scalar transfer, not block_until_ready: on remote-attached platforms
    # only a device→host read is a true execution barrier (same lesson as
    # bench.py's sync comments)
    float(loss)
    dt = time.perf_counter() - t0
    if ckptr is not None:
        ckptr.close()  # drain the async writer before reporting

    if verbose:
        tps = tokens_done / dt
        ms = dt * 1e3 / max(1, args.steps - start_step)
        print(f"final loss {float(loss):.4f}")
        print(f"{tps:,.0f} tokens/sec total ({tps / n:,.0f}/chip, "
              f"{ms:.1f} ms/step)")
        if args.bench and sp > 1:
            # ring/Ulysses sequence parallelism: per-chip residency and
            # wire volume scale with seq/sp, so the measured single-chip
            # envelope (docs/benchmarks.md) projects to sp x that length
            # on a ring of sp chips
            h = cfg.num_heads
            hd = cfg.d_model // h
            blk = (batch // dp) * (seq // sp) * h * hd * 2  # bf16
            print(f"sp={sp}: seq/chip {seq // sp} of {seq} "
                  f"global; ring hop payload {2 * blk / 2 ** 20:.1f} MiB "
                  f"(K+V); projected envelope ≈ sp x single-chip "
                  f"(same per-chip residency)")


if __name__ == "__main__":
    main()
