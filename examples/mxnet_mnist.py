"""Distributed MXNet (gluon) MNIST — reference examples/mxnet_mnist.py
parity: ``DistributedTrainer`` (allreduce gradient exchange instead of
kvstore push/pull), ``broadcast_parameters`` with deferred-init support,
rank-sharded data, final accuracy evaluation.

mxnet is an optional dependency of this framework (the CI image cannot
install it — docs/testing.md records the recipe); without it this
example exits 0 with a SKIP line so ``make examples`` stays green while
still executing the full script wherever mxnet is present.

Usage:
    python examples/mxnet_mnist.py --epochs 2
    bin/hvdrun -np 2 python examples/mxnet_mnist.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

try:
    import mxnet as mx
    from mxnet import autograd, gluon
except ImportError:
    print("SKIP: mxnet is not installed (see docs/testing.md for the "
          "real-mxnet verification recipe)")
    sys.exit(0)

import horovod_tpu.mxnet as hvd


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu mxnet MNIST")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--data", default=None, help="path to mnist .npz")
    p.add_argument("--steps-per-epoch", type=int, default=None)
    return p.parse_args()


def load_data(path, n=4096, n_val=1024):
    if path and os.path.exists(path):
        with np.load(path) as d:
            return ((d["x_train"].astype(np.float32)[:, None] / 255.0,
                     d["y_train"].astype(np.float32)),
                    (d["x_test"].astype(np.float32)[:, None] / 255.0,
                     d["y_test"].astype(np.float32)))
    rng = np.random.RandomState(0)
    return ((rng.rand(n, 1, 28, 28).astype(np.float32),
             rng.randint(0, 10, n).astype(np.float32)),
            (rng.rand(n_val, 1, 28, 28).astype(np.float32),
             rng.randint(0, 10, n_val).astype(np.float32)))


def conv_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(channels=20, kernel_size=5,
                            activation="relu"))
    net.add(gluon.nn.MaxPool2D(pool_size=2, strides=2))
    net.add(gluon.nn.Conv2D(channels=50, kernel_size=5,
                            activation="relu"))
    net.add(gluon.nn.MaxPool2D(pool_size=2, strides=2))
    net.add(gluon.nn.Flatten())
    net.add(gluon.nn.Dense(512, activation="relu"))
    net.add(gluon.nn.Dense(10))
    return net


def evaluate(model, X, Y, batch_size, ctx):
    correct = total = 0
    for i in range(0, len(X) - batch_size + 1, batch_size):
        data = mx.nd.array(X[i:i + batch_size], ctx=ctx)
        out = model(data).asnumpy()
        correct += int((out.argmax(1) == Y[i:i + batch_size]).sum())
        total += batch_size
    return correct / max(1, total)


def main():
    args = parse_args()
    hvd.init()
    ctx = mx.cpu(hvd.local_rank())
    world = hvd.size()

    (X, Y), (Xv, Yv) = load_data(args.data)
    X, Y = X[hvd.rank()::world], Y[hvd.rank()::world]
    steps = args.steps_per_epoch or max(1, len(X) // args.batch_size)

    model = conv_net()
    model.hybridize()
    model.initialize(mx.init.Xavier(), ctx=ctx)
    # touch one forward so deferred shapes exist, then broadcast rank 0's
    # weights (deferred-init parameters broadcast via their _init_impl
    # hook — reference mxnet/__init__.py:106-150)
    model(mx.nd.zeros((1, 1, 28, 28), ctx=ctx))
    hvd.broadcast_parameters(model.collect_params(), root_rank=0)

    trainer = hvd.DistributedTrainer(
        model.collect_params(), "sgd",
        {"learning_rate": args.lr * world, "momentum": args.momentum})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        running = 0.0
        for step in range(steps):
            lo = (step * args.batch_size) % max(1, len(X) - args.batch_size)
            data = mx.nd.array(X[lo:lo + args.batch_size], ctx=ctx)
            label = mx.nd.array(Y[lo:lo + args.batch_size], ctx=ctx)
            with autograd.record():
                loss = loss_fn(model(data), label)
            loss.backward()
            trainer.step(args.batch_size)
            running += float(loss.mean().asscalar())
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {running / steps:.4f}")

    acc = evaluate(model, Xv, Yv, args.batch_size, ctx)
    if hvd.rank() == 0:
        print(f"Validation accuracy: {acc:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
