"""Shared harness for the synthetic image benchmarks
(synthetic_benchmark.py and scaling_benchmark.py): build a data-parallel
train step over the current mesh and time it with the warmup + measured
iterations protocol of the reference harness
(examples/pytorch_synthetic_benchmark.py:24-33 — warmup batches, then
num_iters x num_batches_per_iter timed batches)."""

import time

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import models, trainer


def build_step(model_name, mesh, batch, image_size, fp16_allreduce=False):
    """Compiled data-parallel train step + initial (params, opt_state,
    batch data) for a zoo model on synthetic ImageNet-shaped data."""
    kwargs = {"dropout_rate": 0.0} if model_name.startswith("vgg") else {}
    model = models.build(model_name, num_classes=1000, dtype=jnp.bfloat16,
                         **kwargs)
    images = jnp.zeros((batch, image_size, image_size, 3), jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), images[:2], train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})  # VGG has no BN

    compression = (hvd.Compression.bf16 if fp16_allreduce
                   else hvd.Compression.none)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  compression=compression)
    opt_state = trainer.init_opt_state(tx, params, mesh)

    def loss_fn(p, b):
        imgs, lbls = b
        logits, _ = model.apply(
            {"params": p, "batch_stats": batch_stats}, imgs, train=True,
            mutable=["batch_stats"])
        return trainer.softmax_cross_entropy(logits, lbls)

    step = trainer.make_data_parallel_step(loss_fn, tx, mesh,
                                           compression=compression,
                                           donate=True)
    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    images = jax.device_put(images, sharding)
    labels = jax.device_put(labels, sharding)
    return step, params, opt_state, (images, labels)


def timed_rates(step, params, opt_state, batch_data, batch,
                num_warmup_batches, num_iters, num_batches_per_iter,
                on_iter=None):
    """Run the reference timing protocol; returns per-iteration total
    img/sec. At least one warmup step always runs so trace+compile of the
    jitted step can never land inside the timed region (a compile-polluted
    first iteration would silently wreck the reported rate). The sync
    barrier is a scalar device-to-host read — on remote-attached runtimes
    block_until_ready can return before execution completes
    (docs/benchmarks.md)."""
    for _ in range(max(1, num_warmup_batches)):
        params, opt_state, loss = step(params, opt_state, batch_data)
    float(loss)  # scalar transfer: a sync barrier on every backend

    rates = []
    for i in range(num_iters):
        t0 = time.perf_counter()
        for _ in range(num_batches_per_iter):
            params, opt_state, loss = step(params, opt_state, batch_data)
        float(loss)  # scalar transfer: a sync barrier on every backend
        dt = time.perf_counter() - t0
        rate = batch * num_batches_per_iter / dt
        rates.append(rate)
        if on_iter is not None:
            on_iter(i, rate)
    return rates


def positive_int(value):
    v = int(value)
    if v < 1:
        raise ValueError(f"expected a positive count, got {value}")
    return v
