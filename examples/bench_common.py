"""Shared harness for the synthetic image benchmarks
(synthetic_benchmark.py and scaling_benchmark.py): build a data-parallel
train step over the current mesh and time it with the warmup + measured
iterations protocol of the reference harness
(examples/pytorch_synthetic_benchmark.py:24-33 — warmup batches, then
num_iters x num_batches_per_iter timed batches)."""

import time

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import models, trainer


def build_step(model_name, mesh, batch, image_size, fp16_allreduce=False,
               steps_per_call=1):
    """Compiled data-parallel train step + initial (params, opt_state,
    batch data) for a zoo model on synthetic ImageNet-shaped data.
    ``steps_per_call`` runs that many updates on-device per host call
    (trainer.make_data_parallel_step) — the synthetic-loop form."""
    kwargs = {"dropout_rate": 0.0} if model_name.startswith("vgg") else {}
    model = models.build(model_name, num_classes=1000, dtype=jnp.bfloat16,
                         **kwargs)
    images = jnp.zeros((batch, image_size, image_size, 3), jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), images[:2], train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})  # VGG has no BN

    compression = (hvd.Compression.bf16 if fp16_allreduce
                   else hvd.Compression.none)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  compression=compression)
    opt_state = trainer.init_opt_state(tx, params, mesh)

    def loss_fn(p, b):
        imgs, lbls = b
        logits, _ = model.apply(
            {"params": p, "batch_stats": batch_stats}, imgs, train=True,
            mutable=["batch_stats"])
        return trainer.softmax_cross_entropy(logits, lbls)

    step = trainer.make_data_parallel_step(loss_fn, tx, mesh,
                                           compression=compression,
                                           donate=True,
                                           steps_per_call=steps_per_call)
    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    images = jax.device_put(images, sharding)
    labels = jax.device_put(labels, sharding)
    return step, params, opt_state, (images, labels)


def timed_rates(step, params, opt_state, batch_data, batch,
                num_warmup_batches, num_iters, num_batches_per_iter,
                on_iter=None, updates_per_step=1, return_state=False):
    """Run the reference timing protocol; returns per-iteration total
    img/sec. At least one warmup step always runs so trace+compile of the
    jitted step can never land inside the timed region (a compile-polluted
    first iteration would silently wreck the reported rate). The sync
    barrier is a scalar device-to-host read — on remote-attached runtimes
    block_until_ready can return before execution completes
    (docs/benchmarks.md).

    With return_state=True, returns (rates, params, opt_state) — REQUIRED
    for repeated calls on the same step: the jitted step donates its
    params/opt_state buffers, so re-passing the originals after one call
    is a donated-buffer use error."""
    for _ in range(max(1, num_warmup_batches)):
        params, opt_state, loss = step(params, opt_state, batch_data)
    float(loss)  # scalar transfer: a sync barrier on every backend

    rates = []
    for i in range(num_iters):
        t0 = time.perf_counter()
        for _ in range(num_batches_per_iter):
            params, opt_state, loss = step(params, opt_state, batch_data)
        float(loss)  # scalar transfer: a sync barrier on every backend
        dt = time.perf_counter() - t0
        rate = batch * num_batches_per_iter * updates_per_step / dt
        rates.append(rate)
        if on_iter is not None:
            on_iter(i, rate)
    if return_state:
        return rates, params, opt_state
    return rates


def positive_int(value):
    v = int(value)
    if v < 1:
        raise ValueError(f"expected a positive count, got {value}")
    return v


def transformer_matmul_flops_per_token(cfg, seq):
    """Matmul FLOPs per token — models.transformer.matmul_flops_per_token
    (kept here as the harnesses' historical import point)."""
    from horovod_tpu.models import transformer as tr
    return tr.matmul_flops_per_token(cfg, seq)


def flagship_config(on_tpu=True, **overrides):
    """The canonical flagship bench model: gpt2_small_tpu — GPT-2-small's
    size/FLOPs with the TPU-native 6x128 head shape (head_dim 128 = the
    lane width, so the flash kernels run unpadded; +18% tok/s over 12x64
    measured — see TransformerConfig.gpt2_small_tpu).
    tie_embeddings matches real GPT-2 (shared input/output matrix) and
    is ~3% faster on v5e (no separate [d, vocab] adamw update).
    logits_fp32=False keeps the [B, S, vocab] logits in bf16 —
    trainer.softmax_cross_entropy still accumulates its logsumexp in
    fp32, only the stored logit values round (measured ~4 ms/step at
    this scale; docs/benchmarks.md). ``overrides`` (e.g. flash_variant,
    max_seq_len) go straight into the TransformerConfig — the flash
    ablation leg pins variants through here."""
    from horovod_tpu.models import transformer as tr

    if on_tpu:
        kw = dict(attention_impl="flash", tie_embeddings=True,
                  logits_fp32=False)
        kw.update(overrides)
        return tr.TransformerConfig.gpt2_small_tpu(**kw)
    kw = dict(attention_impl="full")
    kw.update(overrides)
    return tr.TransformerConfig.tiny(**kw)


def build_transformer_step(mesh, batch, seq, cfg=None, on_tpu=True,
                           n_steps=None, vocab_chunk=0):
    """Compiled GSPMD train step + initial state for the flagship
    transformer LM — the ONE setup recipe (model/init/optimizer/token
    generation) shared by bench.py's MFU line and scaling_benchmark
    --model transformer, so the harnesses cannot drift.

    ``n_steps=None`` returns a per-call step (make_gspmd_step) with
    tokens [batch, seq]; ``n_steps=k`` returns the device-side scan
    (make_gspmd_multi_step) with tokens [k, batch, seq].
    Returns (step, params, opt_state, tokens, cfg)."""
    import numpy as np
    import optax

    from horovod_tpu.models import transformer as tr

    if cfg is None:
        cfg = flagship_config(on_tpu)
    model = tr.TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, seq), jnp.int32))["params"]
    # bf16 first moment (PaLM-style): halves the momentum state's HBM
    # traffic through the bandwidth-bound fused grad+AdamW updates —
    # measured -5 ms/step (+7% tok/s) at flagship scale on v5e with
    # loss identical to 3 decimals; second moment stays fp32 (its
    # dynamic range matters, the first moment's doesn't)
    tx = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    make = (trainer.make_gspmd_step if n_steps is None
            else trainer.make_gspmd_multi_step)
    step, pshard, bshard = make(
        tr.lm_loss_fn(model, vocab_chunk=vocab_chunk), tx, mesh,
        tr.param_specs(params), tr.batch_spec(), params=params)
    params = jax.tree_util.tree_map(jax.device_put, params, pshard)
    opt_state = trainer.init_opt_state(tx, params, mesh,
                                       tr.param_specs(params))
    rng = np.random.RandomState(0)
    shape = (batch, seq) if n_steps is None else (n_steps, batch, seq)
    toks = jax.device_put(
        jnp.asarray(rng.randint(0, cfg.vocab_size, shape,
                                dtype=np.int64).astype(np.int32)), bshard)
    return step, params, opt_state, toks, cfg


def setup_transformer_lm(on_tpu, seq=None, flash_variant=None,
                         batch_per_chip=None):
    """Build the flagship-transformer bench (the canonical source of the
    tokens/sec/chip + MFU numbers in bench.py's JSON line and
    docs/benchmarks.md — keep single-sourced so harnesses cannot drift).

    Uses the device-side multi-step loop (trainer.make_gspmd_multi_step)
    so host dispatch — ~3-5 ms per call through a remote-attached
    runtime — is amortized out of the measurement; the loop scans over a
    stacked [n_steps, batch, seq] token array, a real optimizer update
    per inner step.

    ``seq`` / ``flash_variant`` / ``batch_per_chip`` override the
    flagship defaults — the flash-ablation leg builds one window per
    (variant, seq) operating point through exactly this recipe, so the
    ablation and the headline number can never measure different setups.

    Returns (window_fn, meta): window_fn() runs one timed window and
    returns seconds/step; the first call includes compile (callers
    treat it as warmup). Exposing windows individually lets bench.py
    INTERLEAVE them with the ResNet windows so session drift is
    common-mode across both headline numbers."""
    from horovod_tpu.parallel import mesh as mesh_mod

    if on_tpu:
        # batch 16 is the measured per-chip sweet spot (r4: 0.632 MFU vs
        # 0.603 at batch 8 and 0.58 at batch 32, docs/benchmarks.md)
        defaults = (16, 1024, 10)
    else:  # CI smoke on CPU: tiny everything, no MFU claim
        defaults = (2, 64, 2)
    batch_per_chip = batch_per_chip or defaults[0]
    seq = seq or defaults[1]
    inner = defaults[2]

    overrides = {}
    if flash_variant is not None:
        overrides["flash_variant"] = flash_variant
    if on_tpu and seq > 1024:
        overrides["max_seq_len"] = seq
    cfg = flagship_config(on_tpu, **overrides)

    n = hvd.size()
    mesh = mesh_mod.build_mesh(dp=n)
    batch = batch_per_chip * n
    step, params, opt_state, toks, cfg = build_transformer_step(
        mesh, batch, seq, cfg=cfg, on_tpu=on_tpu, n_steps=inner)
    live = {"params": params, "opt": opt_state}

    def window():
        t0 = time.perf_counter()
        live["params"], live["opt"], loss = step(live["params"],
                                                 live["opt"], toks)
        float(loss)  # scalar read = true barrier on remote runtimes
        return (time.perf_counter() - t0) / inner

    meta = {"batch": batch, "batch_per_chip": batch_per_chip, "seq": seq,
            "inner": inner, "cfg": cfg, "n": n,
            "flash_variant": flash_variant or "auto",
            "model": f"gpt2-small-{'tpu-flash' if on_tpu else 'tiny-smoke'}"}
    return window, meta


def transformer_lm_metrics(window_s, meta, peak_flops=None):
    """Fold per-window seconds/step into the bench's metrics dict.
    tokens_per_sec_per_chip/mfu keep the best-window convention (r3/r4
    comparability); the paired-measurement bound rides alongside as
    ms_per_step_mean/pm so cross-round deltas can be judged against
    session drift."""
    best = min(window_s)
    mean = sum(window_s) / len(window_s)
    pm = (max(window_s) - min(window_s)) / 2
    tps_chip = meta["batch"] * meta["seq"] / best / meta["n"]
    flops_per_token = transformer_matmul_flops_per_token(
        meta["cfg"], meta["seq"])
    mfu = (tps_chip * flops_per_token / peak_flops) if peak_flops else None
    return {
        "model": meta["model"],
        "tokens_per_sec_per_chip": round(tps_chip, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "seq_len": meta["seq"],
        "batch_per_chip": meta["batch_per_chip"],
        "ms_per_step": round(best * 1e3, 2),
        "ms_per_step_mean": round(mean * 1e3, 2),
        "ms_per_step_pm": round(pm * 1e3, 2),
        "windows": len(window_s),
    }


def bench_transformer_lm(on_tpu, peak_flops=None):
    """Sequential-windows convenience wrapper over setup/window/metrics
    (bench.py interleaves the windows itself)."""
    window, meta = setup_transformer_lm(on_tpu)
    window()  # compile + warmup
    windows = 3 if on_tpu else 1
    return transformer_lm_metrics([window() for _ in range(windows)],
                                  meta, peak_flops=peak_flops)


# ---------------------------------------------------------------------------
# Eager-allreduce training steps — the autotuner's regime.
#
# The GSPMD steps above average gradients with an in-graph psum, which the
# eager coordination core (and therefore HOROVOD_AUTOTUNE's passive scorer)
# never sees. These builders produce the eager form: per-shard gradients
# computed STACKED — vmap over a [world, per_shard, ...] batch, so every
# gradient leaf has leading dim == hvd.size() and rides the eager core's
# fused stacked-allreduce path (ops/eager.py), the exact path the tuner's
# burst bench exercises — then one optimizer apply on the averaged row.
# Shared by examples/{transformer_lm,synthetic_benchmark}.py
# --eager-allreduce and bench.py's autotune train leg, so the tuner is
# scored on the same step recipe users run.
# ---------------------------------------------------------------------------


def build_eager_lm_step(cfg, world, batch_per_shard, seq, lr=3e-4,
                        tx=None, params=None):
    """Transformer train step with EAGER gradient averaging.
    Returns (step, params, opt_state, toks); step(params, opt_state,
    toks) -> (params, opt_state, loss), toks [world, batch_per_shard,
    seq]. Pass ``tx``/``params`` to reuse a caller's optimizer and
    initialized weights (examples/transformer_lm.py --eager-allreduce)."""
    import numpy as np

    from horovod_tpu.models import transformer as tr

    model = tr.TransformerLM(cfg)
    if params is None:
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((2, seq), jnp.int32))["params"]
    if tx is None:
        tx = optax.adamw(lr, mu_dtype=jnp.bfloat16)
    opt_state = tx.init(params)
    loss_fn = tr.lm_loss_fn(model)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(
        0, cfg.vocab_size, (world, batch_per_shard, seq),
        dtype=np.int64).astype(np.int32))
    return (_eager_step(loss_fn, tx), params, opt_state, toks)


def build_eager_image_step(model_name, world, batch_per_shard, image_size,
                           compression=None):
    """Image-model (ResNet et al) train step with EAGER gradient
    averaging; batch data is [world, batch_per_shard, H, W, 3]."""
    from horovod_tpu import models, trainer as trainer_mod

    kwargs = {"dropout_rate": 0.0} if model_name.startswith("vgg") else {}
    model = models.build(model_name, num_classes=1000, dtype=jnp.bfloat16,
                         **kwargs)
    images = jnp.zeros((world, batch_per_shard, image_size, image_size, 3),
                       jnp.bfloat16)
    labels = jnp.zeros((world, batch_per_shard), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), images[0, :2],
                           train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(p, batch):
        imgs, lbls = batch
        logits, _ = model.apply(
            {"params": p, "batch_stats": batch_stats}, imgs, train=True,
            mutable=["batch_stats"])
        return trainer_mod.softmax_cross_entropy(logits, lbls)

    step = _eager_step(loss_fn, tx, compression=compression)
    return step, params, opt_state, (images, labels)


def _eager_step(loss_fn, tx, compression=None):
    """The shared eager-dp step: jitted vmap'd per-shard grads (stacked
    [world, ...] leaves), ONE eager fused allreduce between compute and
    apply, jitted apply on the averaged row-0 grads."""
    grad_fn = jax.jit(jax.vmap(jax.value_and_grad(loss_fn),
                               in_axes=(None, 0)))
    compression = compression or hvd.Compression.none

    @jax.jit
    def apply_fn(params, opt_state, grads):
        g0 = jax.tree_util.tree_map(lambda g: g[0], grads)
        updates, opt_state = tx.update(g0, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def step(params, opt_state, batch):
        losses, grads = grad_fn(params, batch)
        # the eager core: every leaf is [world, ...] -> stacked kind,
        # fused by the live fusion_threshold/cycle_time knobs, scored
        # passively by the autotuner when HOROVOD_AUTOTUNE=1
        grads = hvd.allreduce_gradients(grads, compression=compression)
        params, opt_state = apply_fn(params, opt_state, grads)
        return params, opt_state, jnp.mean(losses)

    return step
