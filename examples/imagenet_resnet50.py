"""Distributed ImageNet ResNet-50 training.

Capability parity with the reference's examples/pytorch_imagenet_resnet50.py
and keras_imagenet_resnet50.py: per-worker batch sharding, LR = base_lr x
world size with gradual warmup over the first epochs (Goyal et al., the
LearningRateWarmupCallback semantics incl. momentum correction), step decay
at epochs 30/60/80, weight decay, optional fp16/bf16 gradient compression
(--fp16-allreduce), gradient accumulation (--batches-per-allreduce),
validation-accuracy averaging across workers (MetricAverageCallback), and
rank-0 checkpoint/resume per epoch.

Runs on real ImageNet if a directory of .npz shard files is given
(--train-dir), otherwise on synthetic ImageNet-shaped data (this container
has no dataset), which exercises every distributed code path at the real
tensor shapes.

Usage:
    python examples/imagenet_resnet50.py --epochs 2 --steps-per-epoch 10
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/imagenet_resnet50.py --epochs 2 --steps-per-epoch 4 \
        --batch-size 4 --image-size 64
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import callbacks as cb
from horovod_tpu import trainer
from horovod_tpu.models import resnet
from horovod_tpu.utils import checkpoint


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu ImageNet ResNet-50")
    p.add_argument("--model", default="resnet50", choices=sorted(resnet.MODELS))
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-worker batch size")
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--base-lr", type=float, default=0.0125,
                   help="per-worker LR; scaled by world size")
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=0.00005)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="compress gradients to bf16 on the wire")
    p.add_argument("--batches-per-allreduce", type=int, default=1)
    p.add_argument("--checkpoint-dir", default="./imagenet-ckpt")
    p.add_argument("--train-dir", default=None,
                   help="directory of npz shards with images/labels arrays")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--val-steps", type=int, default=2)
    p.add_argument("--seed", type=int, default=42)
    return p.parse_args()


def synthetic_batch(rng, n, size):
    imgs = rng.rand(n, size, size, 3).astype(np.float32)
    labels = rng.randint(0, 1000, n).astype(np.int32)
    return imgs, labels


def load_train_dir(path):
    """Concatenate every .npz shard (arrays 'images' [N,H,W,3] float or
    uint8, 'labels' [N]) under ``path``."""
    shards = sorted(f for f in os.listdir(path) if f.endswith(".npz"))
    if not shards:
        raise SystemExit(f"--train-dir {path}: no .npz shards found")
    imgs, labels = [], []
    for f in shards:
        with np.load(os.path.join(path, f)) as d:
            imgs.append(d["images"].astype(np.float32))
            labels.append(d["labels"].astype(np.int32))
    imgs = np.concatenate(imgs)
    if imgs.max() > 1.5:        # uint8-ranged pixels
        imgs /= 255.0
    return imgs, np.concatenate(labels)


def data_batch(data, rng, n):
    imgs, labels = data
    idx = rng.randint(0, len(imgs), n)
    return imgs[idx], labels[idx]


def main():
    args = parse_args()
    hvd.init()
    world = hvd.size()
    global_batch = args.batch_size * world
    verbose = hvd.process_rank() == 0
    if verbose:
        print(f"workers={world} global_batch={global_batch} "
              f"platform={jax.devices()[0].platform}")

    model = resnet.MODELS[args.model](num_classes=1000, dtype=jnp.bfloat16)
    variables = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((2, args.image_size, args.image_size, 3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]

    compression = (hvd.Compression.bf16 if args.fp16_allreduce
                   else hvd.Compression.none)
    # inject_hyperparams exposes learning_rate to the LR callbacks, the
    # same knob the reference callbacks mutate on the Keras optimizer.
    tx = hvd.DistributedOptimizer(
        optax.inject_hyperparams(optax.sgd)(
            learning_rate=args.base_lr * world, momentum=args.momentum),
        compression=compression,
        backward_passes_per_step=args.batches_per_allreduce)
    opt_state = trainer.init_opt_state(tx, params, hvd.mesh())

    start_epoch = 0
    if checkpoint.exists(args.checkpoint_dir):
        (params, batch_stats, opt_state), start_epoch = checkpoint.restore(
            args.checkpoint_dir, like=(params, batch_stats, opt_state))
        if verbose:
            print(f"resumed from epoch {start_epoch}")

    axis = hvd.mesh().axis_names[0]

    def train_step(params, batch_stats, opt_state, batch):
        imgs, labels = batch

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, imgs,
                train=True, mutable=["batch_stats"])
            ce = trainer.softmax_cross_entropy(logits, labels)
            l2 = 0.5 * sum(jnp.sum(jnp.square(w))
                           for w in jax.tree_util.tree_leaves(p))
            return ce + args.wd * l2, mut["batch_stats"]

        # grads must be per-worker when they reach the DistributedOptimizer
        # (replicated params would make autodiff pre-sum them — see
        # hvd.ensure_varying)
        vparams = jax.tree_util.tree_map(
            lambda p: hvd.ensure_varying(p, axis), params)
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(vparams)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # keep BN statistics identical across replicas (the reference
        # broadcasts them with broadcast_parameters; averaging per step is
        # the sync-BN-statistics variant)
        new_bs = jax.tree_util.tree_map(
            lambda x: jax.lax.pmean(x, axis), new_bs)
        return new_params, new_bs, new_opt, jax.lax.pmean(loss, axis)

    def eval_step(params, batch_stats, batch):
        imgs, labels = batch
        logits = model.apply({"params": params, "batch_stats": batch_stats},
                             imgs, train=False)
        acc = jnp.mean(jnp.argmax(logits, -1) == labels)
        return jax.lax.pmean(acc, axis)

    mesh = hvd.mesh()
    jtrain = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), (P(axis), P(axis))),
        out_specs=(P(), P(), P(), P())))
    jeval = jax.jit(jax.shard_map(
        eval_step, mesh=mesh, in_specs=(P(), P(), (P(axis), P(axis))),
        out_specs=P()))
    sharding = NamedSharding(mesh, P(axis))

    steps = args.steps_per_epoch or max(1, 1281167 // global_batch)
    loop = cb.LoopState(params=params, opt_state=opt_state,
                        steps_per_epoch=steps)
    callbacks = cb.CallbackList([
        cb.BroadcastGlobalVariablesCallback(0),
        cb.LearningRateWarmupCallback(warmup_epochs=args.warmup_epochs,
                                      verbose=verbose),
        # reference pytorch_imagenet_resnet50 step decay: /10 at 30/60/80
        cb.LearningRateScheduleCallback(multiplier=0.1, start_epoch=30,
                                        end_epoch=60),
        cb.LearningRateScheduleCallback(multiplier=0.01, start_epoch=60,
                                        end_epoch=80),
        cb.LearningRateScheduleCallback(multiplier=0.001, start_epoch=80),
        cb.MetricAverageCallback(),
    ], loop)
    callbacks.on_train_begin()
    batch_stats = hvd.broadcast_parameters(batch_stats)

    rng = np.random.RandomState(args.seed + hvd.process_rank())
    data = load_train_dir(args.train_dir) if args.train_dir else None
    for epoch in range(start_epoch, args.epochs):
        callbacks.on_epoch_begin(epoch)
        t0 = time.time()
        losses = []
        for i in range(steps):
            callbacks.on_batch_begin(i)
            imgs, labels = (data_batch(data, rng, global_batch) if data else
                            synthetic_batch(rng, global_batch,
                                            args.image_size))
            imgs = jax.device_put(jnp.asarray(imgs), sharding)
            labels = jax.device_put(jnp.asarray(labels), sharding)
            loop.params, batch_stats, loop.opt_state, loss = jtrain(
                loop.params, batch_stats, loop.opt_state, (imgs, labels))
            losses.append(float(loss))
            callbacks.on_batch_end(i)

        accs = []
        for _ in range(args.val_steps):
            imgs, labels = (data_batch(data, rng, global_batch) if data else
                            synthetic_batch(rng, global_batch,
                                            args.image_size))
            accs.append(float(jeval(
                loop.params, batch_stats,
                (jax.device_put(jnp.asarray(imgs), sharding),
                 jax.device_put(jnp.asarray(labels), sharding)))))

        loop.logs = {"loss": np.mean(losses), "val_acc": np.mean(accs)}
        callbacks.on_epoch_end(epoch, loop.logs)
        if verbose:
            lr = cb.get_hyperparam(loop.opt_state, "learning_rate")
            print(f"epoch {epoch}: loss={loop.logs['loss']:.4f} "
                  f"val_acc={loop.logs['val_acc']:.4f} lr={float(lr):.4f} "
                  f"({time.time() - t0:.1f}s)")
            checkpoint.save(args.checkpoint_dir,
                            (loop.params, batch_stats, loop.opt_state),
                            step=epoch + 1)
    callbacks.on_train_end()


if __name__ == "__main__":
    main()
