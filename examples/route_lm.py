"""Serve the transformer LM behind the router plane (docs/routing.md).

Fronts N serving replicas with one Router and drives the same bimodal
open-loop workload serve_lm.py uses — but through the front door:
every request is dispatched by the routing policy over live load
snapshots, with cache-affinity stickiness on prompt prefixes. With
``--compare`` the SAME workload also runs under round_robin on fresh
replicas, so the load-aware policy's tail-latency win under imbalance
is measured, not asserted. This is the sanctioned client shape hvdlint
HVD017 enforces: examples submit through a Router, never a bare
``ServeEngine.submit``.

Usage:
    # CPU, tiny config, 2 replicas, least_loaded vs round_robin
    JAX_PLATFORMS=cpu python examples/route_lm.py --compare

    # more replicas, heavier traffic
    python examples/route_lm.py --replicas 4 --requests 96 --rate 0.8
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

from horovod_tpu.router import Router
from horovod_tpu.serving.engine import ServeEngine
from horovod_tpu.utils import metrics as hvd_metrics

from serve_lm import make_workload, serving_config

from horovod_tpu.models import transformer as tr


def run_routed(router, workload, max_steps=100000):
    """Drive the router under the arrival schedule: submit every
    request whose arrival step has passed, then step every replica.
    Returns (results, steps, wall_s)."""
    i = 0
    steps = 0
    results = []
    t0 = time.monotonic()
    while i < len(workload) or router.pending():
        while i < len(workload) and workload[i][0] <= steps:
            router.submit(workload[i][1])
            i += 1
        results.extend(router.step())
        steps += 1
        if steps >= max_steps:
            raise RuntimeError(
                f"load never drained in {max_steps} steps "
                f"({len(results)} done)")
    return results, steps, time.monotonic() - t0


def route_workload(cfg, params, workload, policy, replicas, num_slots,
                   max_len, kv_block=8, seed=0):
    """One arm of the comparison: ``replicas`` fresh engines behind a
    fresh Router under ``policy``. Each engine builds its own admission
    queue (HVD_SERVE_QUEUE_DEPTH / HVD_SERVE_ADMISSION_TIMEOUT_S);
    the arms share nothing but params."""
    engines = {
        rid: ServeEngine(cfg, params, num_slots=num_slots,
                         max_len=max_len, kv_block=kv_block, seed=seed)
        for rid in range(replicas)}
    router = Router(engines, policy=policy)
    results, steps, wall_s = run_routed(router, workload)
    completed = [r for r in results if r.outcome == "completed"]
    decode_tokens = sum(len(r.tokens) for r in completed)
    ttfts = sorted(r.ttft_s for r in completed if r.ttft_s is not None)
    by_replica = {}
    for r in completed:
        by_replica[r.replica] = by_replica.get(r.replica, 0) + 1

    def pct(q):
        if not ttfts:
            return None
        return ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))]

    for rid, eng in engines.items():
        assert eng.kv.ledger.blocks_in_use == 0, \
            f"KV blocks leaked on replica {rid}"
    return {
        "policy": policy,
        "replicas": replicas,
        "completed": len(completed),
        "failed": len(results) - len(completed),
        "by_replica": {str(k): v for k, v in sorted(by_replica.items())},
        "decode_tokens": decode_tokens,
        "steps": steps,
        "tokens_per_step": decode_tokens / max(steps, 1),
        "wall_s": round(wall_s, 3),
        "ttft_p50_s": pct(0.50),
        "ttft_p99_s": pct(0.99),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=2,
                    help="decode slots per replica")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--rate", type=float, default=0.5,
                    help="mean arrivals per decode step (open loop)")
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--kv-block", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--policy", default="least_loaded",
                    help="dispatch policy (HVD_ROUTE_POLICY)")
    ap.add_argument("--compare", action="store_true",
                    help="also run the round_robin arm and report the "
                         "p99 TTFT ratio")
    args = ap.parse_args(argv)

    on_tpu = jax.default_backend() == "tpu"
    cfg = serving_config(on_tpu)
    _, params = tr.init_params(cfg, jax.random.PRNGKey(0))
    workload = make_workload(args.seed, args.requests, args.rate)

    out = {"backend": jax.default_backend(),
           "replicas": args.replicas, "slots": args.slots,
           "requests": args.requests, "rate": args.rate}
    out[args.policy] = route_workload(
        cfg, params, workload, args.policy, args.replicas, args.slots,
        args.max_len, kv_block=args.kv_block, seed=args.seed)
    if args.compare and args.policy != "round_robin":
        out["round_robin"] = route_workload(
            cfg, params, workload, "round_robin", args.replicas,
            args.slots, args.max_len, kv_block=args.kv_block,
            seed=args.seed)
        a, b = out[args.policy], out["round_robin"]
        if a["ttft_p99_s"] and b["ttft_p99_s"]:
            out["p99_ttft_ratio"] = round(
                a["ttft_p99_s"] / b["ttft_p99_s"], 3)
    out["metrics"] = hvd_metrics.get_registry().snapshot(max_events=8)
    print(json.dumps(out, default=float))
    return 0


if __name__ == "__main__":
    sys.exit(main())
