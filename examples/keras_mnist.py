"""Distributed Keras MNIST — reference examples/keras_mnist.py parity:
DistributedOptimizer with size-scaled LR, broadcast + metric-average +
LR-warmup callbacks, rank-0 checkpointing. Keras 3 is multi-backend; this
runs on the TF backend by default and on the JAX backend with
KERAS_BACKEND=jax (the TPU-idiomatic pairing).

Usage:
    python examples/keras_mnist.py --epochs 2
    bin/hvdrun -np 2 python examples/keras_mnist.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import horovod_tpu.keras as hvd


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu keras MNIST")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--warmup-epochs", type=int, default=1)
    p.add_argument("--checkpoint-dir", default="./keras-mnist-ckpt")
    p.add_argument("--data", default=None, help="path to mnist .npz")
    p.add_argument("--steps-per-epoch", type=int, default=None)
    return p.parse_args()


def load_data(path, n=8192):
    if path and os.path.exists(path):
        with np.load(path) as d:
            return (d["x_train"].astype(np.float32)[..., None] / 255.0,
                    d["y_train"].astype(np.int64))
    rng = np.random.RandomState(0)
    return (rng.rand(n, 28, 28, 1).astype(np.float32),
            rng.randint(0, 10, n).astype(np.int64))


def main():
    args = parse_args()
    hvd.init()
    import keras

    world = hvd.size()
    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(32, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Conv2D(64, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax")])

    # size-scaled LR + warmup, the reference example's recipe.
    # jit_compile=False: the distributed apply_gradients rides a
    # py_function, which XLA cannot lower (Keras auto-enables XLA on
    # accelerator hosts).
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            keras.optimizers.SGD(args.lr * world,
                                 momentum=args.momentum)),
        loss="sparse_categorical_crossentropy", metrics=["accuracy"],
        jit_compile=False)

    X, Y = load_data(args.data)
    steps = args.steps_per_epoch or max(1, (len(X) // world)
                                        // args.batch_size)
    X, Y = X[hvd.rank()::world], Y[hvd.rank()::world]

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=args.warmup_epochs, steps_per_epoch=steps,
            verbose=1 if hvd.rank() == 0 else 0),
    ]
    if hvd.rank() == 0:
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            os.path.join(args.checkpoint_dir, "checkpoint.keras")))

    model.fit(X, Y, batch_size=args.batch_size, epochs=args.epochs,
              steps_per_epoch=steps, callbacks=callbacks,
              verbose=1 if hvd.rank() == 0 else 0)


if __name__ == "__main__":
    main()
