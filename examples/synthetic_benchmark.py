"""Synthetic ResNet benchmark — parity with the reference harness
(examples/pytorch_synthetic_benchmark.py: --model, --batch-size,
--num-warmup-batches 10, --num-iters 10, --num-batches-per-iter 10; prints
img/sec per worker and total with stddev).

TPU-native: bf16 compute, NHWC, one fused gradient psum per bucket inside a
single compiled train step.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import models

from bench_common import (build_eager_image_step, build_step, positive_int,
                          timed_rates)


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=models.names())
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-worker batch size (reference default 32)")
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-iters", type=positive_int, default=10)
    p.add_argument("--num-batches-per-iter", type=positive_int, default=10)
    p.add_argument("--image-size", type=int, default=None,
                   help="default: the model's canonical size (224; "
                        "inception3 299)")
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="bf16 compression on gradient allreduce")
    p.add_argument("--eager-allreduce", action="store_true",
                   help="average gradients through the EAGER collective "
                        "core per step (reference Horovod's regime, and "
                        "the one HOROVOD_AUTOTUNE scores) instead of the "
                        "in-graph psum")
    args = p.parse_args()
    if args.image_size is None:
        args.image_size = models.image_size(args.model)
    return args


def main():
    args = parse_args()
    hvd.init()
    world = hvd.size()
    batch = args.batch_size * world

    if args.eager_allreduce:
        step, params, opt_state, batch_data = build_eager_image_step(
            args.model, world, args.batch_size, args.image_size,
            compression=hvd.Compression.bf16 if args.fp16_allreduce
            else None)
    else:
        step, params, opt_state, batch_data = build_step(
            args.model, hvd.mesh(), batch, args.image_size,
            fp16_allreduce=args.fp16_allreduce)

    if hvd.process_rank() == 0:
        print(f"Model: {args.model}")
        print(f"Batch size: {args.batch_size} per worker x {world} workers")
        if args.eager_allreduce:
            print("Gradient averaging: eager fused allreduce "
                  "(autotune-scorable)")

    def on_iter(i, rate):
        if hvd.process_rank() == 0:
            print(f"Iter #{i}: {rate / world:.1f} img/sec per worker")

    rates = timed_rates(step, params, opt_state, batch_data, batch,
                        args.num_warmup_batches, args.num_iters,
                        args.num_batches_per_iter, on_iter=on_iter)

    if hvd.process_rank() == 0:
        img_secs = [r / world for r in rates]
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per worker: {mean:.1f} +-{conf:.1f}")
        print(f"Total img/sec on {world} worker(s): "
              f"{mean * world:.1f} +-{conf * world:.1f}")


if __name__ == "__main__":
    main()
