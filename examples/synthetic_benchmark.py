"""Synthetic ResNet benchmark — parity with the reference harness
(examples/pytorch_synthetic_benchmark.py: --model, --batch-size,
--num-warmup-batches 10, --num-iters 10, --num-batches-per-iter 10; prints
img/sec per worker and total with stddev).

TPU-native: bf16 compute, NHWC, one fused gradient psum per bucket inside a
single compiled train step.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import models, trainer


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=models.names())
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-worker batch size (reference default 32)")
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--image-size", type=int, default=None,
                   help="default: the model's canonical size (224; "
                        "inception3 299)")
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="bf16 compression on gradient allreduce")
    args = p.parse_args()
    if args.image_size is None:
        args.image_size = models.image_size(args.model)
    return args


def main():
    args = parse_args()
    hvd.init()
    world = hvd.size()
    batch = args.batch_size * world

    kwargs = {"dropout_rate": 0.0} if args.model.startswith("vgg") else {}
    model = models.build(args.model, num_classes=1000, dtype=jnp.bfloat16,
                         **kwargs)
    images = jnp.zeros((batch, args.image_size, args.image_size, 3),
                       jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), images[:2], train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})  # VGG has no BN

    compression = (hvd.Compression.bf16 if args.fp16_allreduce
                   else hvd.Compression.none)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  compression=compression)
    opt_state = trainer.init_opt_state(tx, params, hvd.mesh())

    def loss_fn(p, b):
        imgs, lbls = b
        logits, _ = model.apply(
            {"params": p, "batch_stats": batch_stats}, imgs, train=True,
            mutable=["batch_stats"])
        return trainer.softmax_cross_entropy(logits, lbls)

    step = trainer.make_data_parallel_step(loss_fn, tx, hvd.mesh(),
                                           compression=compression,
                                           donate=True)
    sharding = NamedSharding(hvd.mesh(), P(hvd.mesh().axis_names[0]))
    images = jax.device_put(images, sharding)
    labels = jax.device_put(labels, sharding)

    if hvd.process_rank() == 0:
        print(f"Model: {args.model}")
        print(f"Batch size: {args.batch_size} per worker x {world} workers")

    for _ in range(args.num_warmup_batches):
        params, opt_state, loss = step(params, opt_state, (images, labels))
    float(loss)  # scalar transfer: a sync barrier on every backend

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params, opt_state, loss = step(params, opt_state,
                                           (images, labels))
        float(loss)  # scalar transfer: a sync barrier on every backend
        rate = batch * args.num_batches_per_iter / (time.perf_counter() - t0)
        img_secs.append(rate / world)
        if hvd.process_rank() == 0:
            print(f"Iter #{i}: {rate / world:.1f} img/sec per worker")

    if hvd.process_rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per worker: {mean:.1f} +-{conf:.1f}")
        print(f"Total img/sec on {world} worker(s): "
              f"{mean * world:.1f} +-{conf * world:.1f}")


if __name__ == "__main__":
    main()
