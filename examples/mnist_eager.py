"""Eager-mode distributed MNIST — the imperative-API workload.

Capability parity with the reference's examples/tensorflow_mnist_eager.py:
no jit'd training step wrapping the collective — gradients are computed per
step and allreduced through the **eager API** (`hvd.allreduce` outside any
traced context), exercising the coordination core: named tensors, cycle
batching, fusion planning, plan cache, timeline. Parameters are broadcast
from rank 0 at step 0 exactly as the reference broadcasts variables after
the first batch.

This is the slow path by design (the jit path is examples/mnist.py); its
value is validating that imperative user code works unchanged.

Usage:
    python examples/mnist_eager.py --steps 50
    HOROVOD_TIMELINE=/tmp/t.json python examples/mnist_eager.py --steps 50
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import trainer
from horovod_tpu.models.mnist import MnistCNN


def parse_args():
    p = argparse.ArgumentParser(description="horovod_tpu eager MNIST")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=42)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    world = hvd.size()
    verbose = hvd.process_rank() == 0

    rng = np.random.RandomState(args.seed)
    X = rng.rand(8192, 28, 28, 1).astype(np.float32)
    Y = ((X.mean(axis=(1, 2, 3)) * 1e4) % 10).astype(np.int32)

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    # LR scales with the number of eager participants — host processes,
    # which is what the eager allreduce averages over (one process may
    # drive several chips; hvd.size() would overscale on a single host).
    tx = optax.sgd(args.lr * hvd.process_count())
    opt_state = tx.init(params)

    # grad of the local loss only — the collective is separate and eager
    @jax.jit
    def local_grads(params, imgs, labels):
        def loss_fn(p):
            return trainer.softmax_cross_entropy(
                model.apply({"params": p}, imgs), labels)
        return jax.value_and_grad(loss_fn)(params)

    t0 = time.time()
    for i in range(args.steps):
        # each process trains on its own shard; the eager allreduce below
        # averages the resulting gradients across processes
        nproc, prank = hvd.process_count(), hvd.process_rank()
        lo = ((i * nproc + prank) * args.batch_size) % (len(X)
                                                        - args.batch_size)
        imgs = X[lo:lo + args.batch_size]
        labels = Y[lo:lo + args.batch_size]

        loss, grads = local_grads(params, jnp.asarray(imgs),
                                  jnp.asarray(labels))

        # EAGER collective: one named allreduce per layer gradient, exactly
        # the reference's per-variable hvd.allreduce in the eager tape loop.
        # The coordination core batches these into one fused cycle.
        flat, treedef = jax.tree_util.tree_flatten(grads)
        # stable names: handles are synchronized within the step, so the
        # same name set recurs every step and hits the plan cache
        handles = [hvd.allreduce_async(g, name=f"grad.{j}", average=True)
                   for j, g in enumerate(flat)]
        flat = [hvd.synchronize(h) for h in handles]
        grads = jax.tree_util.tree_unflatten(treedef, flat)

        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

        if i == 0:
            # broadcast after the first step, reference
            # tensorflow_mnist_eager.py's broadcast_variables placement
            params = hvd.broadcast_parameters(params, root_rank=0)
        if verbose and (i + 1) % 10 == 0:
            print(f"step {i + 1}: loss={float(loss):.4f}")

    if verbose:
        rate = args.steps / (time.time() - t0)
        print(f"{args.steps} eager steps, {rate:.1f} steps/s")


if __name__ == "__main__":
    main()
