# Developer entry points (reference: setup.py + .buildkite/gen-pipeline.sh).

PY ?= python
CPU_ENV = PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
CPU_MESH = $(CPU_ENV) XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: lint lint-concurrency test native bench examples ci clean

# distributed-correctness static analysis (tools/hvdlint, docs/hvdlint.md);
# cheapest gate, so it leads the ci chain
lint:
	$(PY) -m tools.hvdlint horovod_tpu tools bench.py examples
	$(PY) -m tools.hvdlint --check-envdoc

# whole-program lock-discipline pass (docs/concurrency.md): guarded_by
# annotations + LOCK_RANKS order, HVD021/HVD022
lint-concurrency:
	$(PY) -m tools.hvdlint --selftest
	$(PY) -m tools.hvdlint --concurrency

native:
	$(PY) setup.py build_native

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py

# example smoke runs on the virtual 8-worker CPU mesh — the reference CI
# runs its example scripts as integration tests after pytest
# (gen-pipeline.sh:101-128)
examples:
	$(CPU_MESH) $(PY) examples/mnist.py --epochs 1 --steps-per-epoch 4
	$(CPU_MESH) $(PY) examples/mnist_eager.py --steps 20
	$(CPU_MESH) $(PY) examples/word2vec.py --steps 30 --batch-size 32
	$(CPU_MESH) $(PY) examples/imagenet_resnet50.py --epochs 1 \
	    --steps-per-epoch 2 --batch-size 2 --image-size 32 --val-steps 1 \
	    --checkpoint-dir /tmp/hvd-ci-imagenet-ckpt
	$(CPU_MESH) $(PY) examples/transformer_lm.py --size tiny --steps 3 \
	    --dp 2 --tp 2 --sp 2 --attention ring
	$(CPU_MESH) $(PY) examples/serve_lm.py --requests 12 --slots 2 \
	    --max-len 64 --baseline
	$(CPU_MESH) $(PY) examples/route_lm.py --requests 12 --replicas 2 \
	    --slots 2 --max-len 64 --compare
	$(CPU_MESH) $(PY) examples/synthetic_benchmark.py --model resnet18 \
	    --batch-size 1 --image-size 32 --num-warmup-batches 1 \
	    --num-iters 1 --num-batches-per-iter 2
	$(CPU_MESH) $(PY) examples/scaling_benchmark.py --model resnet18 \
	    --batch-size 1 --image-size 32 --device-counts 1,2 \
	    --num-warmup-batches 1 --num-iters 1 --num-batches-per-iter 2
	$(CPU_ENV) $(PY) examples/pytorch_mnist.py \
	    --epochs 1 --steps-per-epoch 4 --checkpoint-dir /tmp/hvd-ci-torch-ckpt
	$(CPU_ENV) $(PY) examples/keras_mnist.py \
	    --epochs 1 --steps-per-epoch 4 --checkpoint-dir /tmp/hvd-ci-keras-ckpt
	# 2-process launch: LearningRateWarmupCallback's ramp is identity at
	# size 1, so the warmup/schedule recipe is exercised across ranks
	$(CPU_ENV) PYTHONPATH=. $(PY) bin/hvdrun -np 2 $(PY) \
	    examples/keras_mnist_advanced.py --epochs 3 --steps-per-epoch 3 \
	    --val-steps 1 --warmup-epochs 2 \
	    --checkpoint-dir /tmp/hvd-ci-keras-adv-ckpt
	$(CPU_ENV) $(PY) examples/mxnet_mnist.py --epochs 1 --steps-per-epoch 4
	$(CPU_MESH) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

ci: lint lint-concurrency native test examples

clean:
	rm -rf build dist *.egg-info /tmp/hvd-ci-imagenet-ckpt \
	    /tmp/hvd-ci-torch-ckpt /tmp/hvd-ci-keras-ckpt \
	    /tmp/hvd-ci-keras-adv-ckpt
